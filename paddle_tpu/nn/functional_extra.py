"""nn.functional — second tranche: the remaining reference functional
surface (python/paddle/nn/functional/__init__.py names absent from
functional.py). Pool/pad/shuffle forms delegate to the corresponding
layers (layers_extra.py), losses and attention helpers are implemented
here over jnp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _random
from ..core.tensor import Tensor

__all__ = [
    "max_pool2d", "max_pool1d_with_mask",
    "conv1d_transpose", "conv3d_transpose", "pairwise_distance",
    "elu_", "hardtanh_", "leaky_relu_", "tanh_", "thresholded_relu",
    "thresholded_relu_", "dropout2d", "dropout3d", "feature_alpha_dropout",
    "zeropad2d", "upsample", "bilinear", "avg_pool3d", "lp_pool1d",
    "lp_pool2d", "max_pool3d", "max_unpool1d", "max_unpool2d",
    "max_unpool3d", "adaptive_avg_pool1d", "adaptive_avg_pool3d",
    "adaptive_max_pool1d", "adaptive_max_pool3d", "fractional_max_pool2d",
    "fractional_max_pool3d", "dice_loss", "hsigmoid_loss", "log_loss",
    "margin_ranking_loss", "multi_label_soft_margin_loss",
    "poisson_nll_loss", "npair_loss", "sigmoid_focal_loss",
    "margin_cross_entropy", "square_error_cost", "ctc_loss", "rnnt_loss",
    "pixel_unshuffle", "channel_shuffle", "gather_tree", "temporal_shift",
    "class_center_sample", "sparse_attention", "fold",
    "cosine_embedding_loss", "rrelu", "triplet_margin_with_distance_loss",
    "triplet_margin_loss", "adaptive_log_softmax_with_loss",
    "multi_margin_loss", "soft_margin_loss", "gaussian_nll_loss",
    "flashmask_attention", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _t(v):
    return Tensor._from_value(v)


def _dispatch(fn, *tensors, **attrs):
    from .layers_extra import _dispatch as _d

    return _d(fn, *tensors, **attrs)


# ------------------------------------------------------- layer delegations

def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL"):
    from jax import lax

    from .layers_extra import _dispatch

    stride_t = (stride,) if isinstance(stride, int) else tuple(stride)
    k = weight.shape[2]
    p = padding if isinstance(padding, int) else padding[0]

    def fn(v, w, b):
        out = lax.conv_transpose(
            v, jnp.transpose(w, (2, 1, 0)),
            strides=stride_t, padding=[(k - 1 - p, k - 1 - p)],
            dimension_numbers=("NCH", "HIO", "NCH"),
            transpose_kernel=True)
        if b is not None:
            out = out + b.reshape(1, -1, 1)
        return out

    return _dispatch(fn, x, weight, bias)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW"):
    from jax import lax

    from .layers_extra import _dispatch

    stride_t = ((stride,) * 3 if isinstance(stride, int) else tuple(stride))
    ks = weight.shape[2:]
    ps = ((padding,) * 3 if isinstance(padding, int) else tuple(padding))
    pads = [(k - 1 - p, k - 1 - p) for k, p in zip(ks, ps)]

    def fn(v, w, b):
        out = lax.conv_transpose(
            v, jnp.transpose(w, (2, 3, 4, 1, 0)),
            strides=stride_t, padding=pads,
            dimension_numbers=("NCDHW", "DHWIO", "NCDHW"),
            transpose_kernel=True)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1, 1)
        return out

    return _dispatch(fn, x, weight, bias)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False):
    def fn(a, b):
        d = a - b + epsilon
        out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        return out[..., None] if keepdim else out

    return _dispatch(fn, x, y)


# --------------------------------------------------------- activations

def thresholded_relu(x, threshold=1.0, value=0.0):
    return _dispatch(lambda v: jnp.where(v > threshold, v, value), x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    if training:
        key = _random.next_key()
        slope = jax.random.uniform(key, _v(x).shape, minval=lower,
                                   maxval=upper)
    else:
        slope = (lower + upper) / 2.0
    return _dispatch(lambda v: jnp.where(v >= 0, v, slope * v), x)


def _inplace(fn):
    def inner(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        x._value = out._value
        return x

    return inner


def _elu(x, alpha=1.0):
    from . import functional as F

    return F.elu(x, alpha)


def _hardtanh(x, min=-1.0, max=1.0):
    from . import functional as F

    return F.hardtanh(x, min, max)


def _leaky_relu(x, negative_slope=0.01):
    from . import functional as F

    return F.leaky_relu(x, negative_slope)


def _tanh(x):
    from . import functional as F

    return F.tanh(x)


elu_ = _inplace(_elu)
hardtanh_ = _inplace(_hardtanh)
leaky_relu_ = _inplace(_leaky_relu)
tanh_ = _inplace(_tanh)
thresholded_relu_ = _inplace(thresholded_relu)


# ------------------------------------------------------------ dropout/pad

def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    from . import functional as F

    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return F.dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    from . import functional as F

    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return F.dropout(x, p=p, axis=axis, training=training)


def feature_alpha_dropout(x, p=0.5, training=True):
    from .layers_extra import FeatureAlphaDropout

    layer = FeatureAlphaDropout(p)
    layer.training = training
    return layer(x)


def zeropad2d(x, padding, data_format="NCHW"):
    from .layers_extra import ZeroPad2D

    return ZeroPad2D(padding, data_format=data_format)(x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW"):
    from . import functional as F

    return F.interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                         align_corners=align_corners,
                         data_format=data_format)


def bilinear(x1, x2, weight, bias=None):
    def fn(a, b, w, bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out if bb is None else out + bb

    return _dispatch(fn, x1, x2, weight, bias)


# --------------------------------------------------------------- pooling

def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, data_format="NCDHW"):
    from .layers_extra import AvgPool3D

    return AvgPool3D(kernel_size, stride, padding)(x)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW"):
    """max_pool2d with the reference's return_mask form (argmax indices
    for the unpool round-trip); plain calls go straight to the op."""
    from ..ops import max_pool2d as _op

    out = _op(x, kernel_size, stride=stride, padding=padding,
              ceil_mode=ceil_mode, data_format=data_format)
    if return_mask:
        return out, _max_pool_indices(x, out, kernel_size, stride, padding,
                                      ndim=2)
    return out


def max_pool1d_with_mask(x, kernel_size, stride=None, padding=0):
    from ..ops import max_pool1d as _op

    out = _op(x, kernel_size, stride=stride, padding=padding)
    return out, _max_pool_indices(x, out, kernel_size, stride, padding,
                                  ndim=1)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW"):
    from .layers_extra import MaxPool3D

    out = MaxPool3D(kernel_size, stride, padding)(x)
    if return_mask:
        return out, _max_pool_indices(x, out, kernel_size, stride, padding,
                                      ndim=3)
    return out


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL"):
    from .layers_extra import LPPool1D

    return LPPool1D(norm_type, kernel_size, stride, padding)(x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW"):
    from .layers_extra import LPPool2D

    return LPPool2D(norm_type, kernel_size, stride, padding)(x)


def adaptive_avg_pool1d(x, output_size):
    from .layers_extra import AdaptiveAvgPool1D

    return AdaptiveAvgPool1D(output_size)(x)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    from .layers_extra import AdaptiveAvgPool3D

    return AdaptiveAvgPool3D(output_size)(x)


def adaptive_max_pool1d(x, output_size, return_mask=False):
    from .layers_extra import AdaptiveMaxPool1D

    return AdaptiveMaxPool1D(output_size)(x)


def adaptive_max_pool3d(x, output_size, return_mask=False):
    from .layers_extra import AdaptiveMaxPool3D

    return AdaptiveMaxPool3D(output_size)(x)


def _pool_regions(in_size, out_size, random_u):
    """Fractional-pooling region boundaries (Graham 2014, the reference's
    fractional_max_pool*): pseudo-random sequence from one uniform draw."""
    alpha = in_size / out_size
    import numpy as np

    u = random_u if random_u is not None else float(np.random.uniform())
    idx = np.ceil(alpha * (np.arange(out_size) + u)).astype(int) - \
        int(np.ceil(alpha * u) - 1) - 1
    starts = np.clip(idx, 0, in_size - 1)
    ends = np.concatenate([starts[1:], [in_size]])
    ends = np.maximum(ends, starts + 1)
    return starts, ends


def _fractional_pool(x, output_size, random_u, spatial_ndim):
    spatial = _v(x).shape[-spatial_ndim:]
    ndim = _v(x).ndim
    if isinstance(output_size, int):
        output_size = (output_size,) * spatial_ndim
    regions = [
        _pool_regions(in_s, out_s, random_u)
        for in_s, out_s in zip(spatial, output_size)
    ]

    def fn(v):
        slabs = v
        for d, (starts, ends) in enumerate(regions):
            axis = ndim - spatial_ndim + d
            pieces = [
                jnp.max(jnp.take(slabs, jnp.arange(s, e), axis=axis),
                        axis=axis, keepdims=True)
                for s, e in zip(starts, ends)
            ]
            slabs = jnp.concatenate(pieces, axis=axis)
        return slabs

    return _dispatch(fn, x)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False):
    return _fractional_pool(x, output_size, random_u, 2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False):
    return _fractional_pool(x, output_size, random_u, 3)


def _max_pool_indices(x, out, kernel_size, stride, padding, ndim):
    # flat indices of each maximum within the input spatial volume
    v, o = _v(x), _v(out)
    # nearest-match scan: for parity APIs only (reference returns argmax ids)
    flat_sp = 1
    for s in v.shape[-ndim:]:
        flat_sp *= s
    vf = v.reshape(v.shape[:-ndim] + (flat_sp,))
    idx = jnp.argmax(
        (vf[..., None, :] == o.reshape(o.shape[:-ndim] + (1, -1,))
         .swapaxes(-1, -2)).astype(jnp.int32), axis=-1)
    return _t(idx.reshape(o.shape).astype(jnp.int64))


def _unpool(x, indices, spatial_out, ndim):
    ind = _v(indices)
    lead = _v(x).shape[:-ndim]
    flat_out = 1
    for s in spatial_out:
        flat_out *= s
    flat_lead = 1
    for s in lead:
        flat_lead *= s
    inf = ind.reshape(flat_lead, -1).astype(jnp.int32)

    def fn(v):
        vf = v.reshape(flat_lead, -1)
        out = jnp.zeros((flat_lead, flat_out), v.dtype)
        out2 = jax.vmap(lambda o, i, val: o.at[i].set(val))(out, inf, vf)
        return out2.reshape(lead + tuple(spatial_out))

    return _dispatch(fn, x)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL"):
    stride = stride or kernel_size
    L = (output_size[-1] if output_size
         else (x.shape[-1] - 1) * stride + kernel_size - 2 * padding)
    return _unpool(x, indices, (L,), 1)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size, kernel_size)
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride, stride)
    if output_size:
        hw = tuple(output_size)[-2:]
    else:
        hw = tuple((x.shape[-2 + i] - 1) * stride[i] + kernel_size[i]
                   - 2 * padding for i in range(2))
    return _unpool(x, indices, hw, 2)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW"):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * 3
    stride = stride or kernel_size
    if isinstance(stride, int):
        stride = (stride,) * 3
    if output_size:
        dhw = tuple(output_size)[-3:]
    else:
        dhw = tuple((x.shape[-3 + i] - 1) * stride[i] + kernel_size[i]
                    - 2 * padding for i in range(3))
    return _unpool(x, indices, dhw, 3)


# ----------------------------------------------------------------- losses

def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def square_error_cost(input, label):
    return _dispatch(lambda a, b: (a - b) * (a - b), input, label)


def log_loss(input, label, epsilon=1e-4):
    return _dispatch(
        lambda p, y: -y * jnp.log(p + epsilon)
        - (1 - y) * jnp.log1p(epsilon - p), input, label)


def dice_loss(input, label, epsilon=1e-5):
    def fn(p, lab):
        y = jax.nn.one_hot(lab[..., 0], p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * y, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(y, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return _dispatch(fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):
    return _dispatch(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin),
                                reduction), input, other, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean"):
    def fn(x, y, w):
        loss = -(y * jax.nn.log_sigmoid(x)
                 + (1 - y) * jax.nn.log_sigmoid(-x))
        if w is not None:
            loss = loss * w
        return _reduce(jnp.mean(loss, axis=-1), reduction)

    return _dispatch(fn, input, label, weight)


def soft_margin_loss(input, label, reduction="mean"):
    return _dispatch(
        lambda x, y: _reduce(jnp.log1p(jnp.exp(-y * x)), reduction),
        input, label)


def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean"):
    def fn(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return _dispatch(fn, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, yv):
        y = yv.reshape(-1)
        sim = a @ p.T
        same = (y[:, None] == y[None, :]).astype(a.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        xent = jnp.mean(
            jnp.sum(-same * jax.nn.log_softmax(sim, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                        + jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return xent + reg

    return _dispatch(fn, anchor, positive, labels)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum"):
    def fn(x, y, norm):
        p = jax.nn.sigmoid(x)
        ce = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if norm is not None:
            loss = loss / norm
        return _reduce(loss, reduction)

    return _dispatch(fn, logit, label, normalizer)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean"):
    from .layers_extra import CosineEmbeddingLoss

    return CosineEmbeddingLoss(margin=margin, reduction=reduction)(
        input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean"):
    from .layers_extra import TripletMarginLoss

    return TripletMarginLoss(margin=margin, p=p, epsilon=epsilon, swap=swap,
                             reduction=reduction)(input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean"):
    from .layers_extra import TripletMarginWithDistanceLoss

    return TripletMarginWithDistanceLoss(
        distance_function=distance_function, margin=margin, swap=swap,
        reduction=reduction)(input, positive, negative)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean"):
    from .layers_extra import GaussianNLLLoss

    return GaussianNLLLoss(full=full, epsilon=epsilon,
                           reduction=reduction)(input, label, variance)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean"):
    from .layers_extra import MultiMarginLoss

    return MultiMarginLoss(p=p, margin=margin, weight=weight,
                           reduction=reduction)(input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    from .layers_extra import CTCLoss

    return CTCLoss(blank=blank, reduction=reduction)(
        log_probs, labels, input_lengths, label_lengths,
        norm_by_times=norm_by_times)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean"):
    """RNN-T transducer loss (reference rnnt_loss, warprnnt kernel):
    log-space forward DP over the (T, U) lattice, vectorized over U with a
    lax.scan over T."""
    y = _v(label).astype(jnp.int32)  # (B, U)
    t_len = _v(input_lengths).astype(jnp.int32)
    u_len = _v(label_lengths).astype(jnp.int32)

    def fn(logits):
        return _rnnt_forward(logits, y, t_len, u_len, blank, reduction)

    return _dispatch(fn, input)


def _rnnt_forward(logits, y, t_len, u_len, blank, reduction):
    logp = jax.nn.log_softmax(logits, axis=-1)
    B, T, U1, V = logp.shape
    U = U1 - 1
    NEG = -1e30

    blank_lp = logp[..., blank]  # (B, T, U+1)
    lab_lp = jnp.take_along_axis(
        logp[:, :, :U, :], y[:, None, :, None].repeat(T, 1), axis=-1
    )[..., 0]  # (B, T, U) emit prob of label u at (t, u)

    u_idx = jnp.arange(U1)

    def step(alpha_prev, t):
        # alpha over u for this t: horizontal (blank from t-1,u) then
        # vertical (emit from t,u-1) via associative scan substitute:
        horiz = jnp.where(t == 0,
                          jnp.where(u_idx[None, :] == 0, 0.0, NEG),
                          alpha_prev + blank_lp[:, jnp.maximum(t - 1, 0), :])
        # sequential emit along u: alpha[u] = logaddexp(horiz[u],
        # alpha[u-1] + lab_lp[t, u-1]) — a scan over U (small)
        def emit(carry, u):
            a_prev = carry
            val = jnp.logaddexp(
                horiz[:, u],
                jnp.where(u > 0,
                          a_prev + lab_lp[:, t, jnp.maximum(u - 1, 0)], NEG))
            return val, val

        _, cols = jax.lax.scan(emit, jnp.full((B,), NEG), u_idx)
        alpha = cols.T  # (B, U+1)
        return alpha, alpha

    _, alphas = jax.lax.scan(step, jnp.full((B, U1), NEG), jnp.arange(T))
    # (T, B, U+1): total = alpha[t_len-1, u_len] + blank at the end
    alphas = alphas.transpose(1, 0, 2)  # (B, T, U+1)
    b_idx = jnp.arange(B)
    final = alphas[b_idx, t_len - 1, u_len] + blank_lp[b_idx, t_len - 1,
                                                       u_len]
    loss = -final
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False):
    """Hierarchical sigmoid over the default complete binary tree
    (reference hsigmoid_loss): class c's path is the binary expansion of
    c + num_classes down from the root."""
    y = _v(label).astype(jnp.int32).reshape(-1)
    import math as _math

    depth = max(int(_math.ceil(_math.log2(num_classes))), 1)

    def fn(x, w, b):
        codes = y + num_classes
        losses = []
        node = codes
        for _ in range(depth):
            bit = node % 2
            parent = node // 2
            # internal node ids are 1..num_classes-1 → rows of weight
            logit = jnp.einsum("bd,bd->b", x,
                               w[jnp.clip(parent - 1, 0, w.shape[0] - 1)])
            if b is not None:
                logit = logit + b.reshape(-1)[
                    jnp.clip(parent - 1, 0, b.size - 1)]
            sign = 1.0 - 2.0 * bit.astype(x.dtype)  # bit 0 → +1, bit 1 → -1
            step_loss = -jax.nn.log_sigmoid(sign * logit)
            valid = parent >= 1
            losses.append(jnp.where(valid, step_loss, 0.0))
            node = parent
        return jnp.mean(jnp.sum(jnp.stack(losses, -1), -1))

    return _dispatch(fn, input, weight, bias)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference margin_cross_entropy;
    single-group form — the model-parallel group path shards classes)."""
    y = _v(label).astype(jnp.int32).reshape(-1)

    def fn(x):
        theta = jnp.arccos(jnp.clip(x, -1.0, 1.0))
        onehot = jax.nn.one_hot(y, x.shape[-1], dtype=x.dtype)
        margin_cos = jnp.cos(margin1 * theta + margin2) - margin3
        adjusted = jnp.where(onehot > 0, margin_cos, x) * scale
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
        loss = _reduce(loss, reduction)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return _dispatch(fn, logits)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers (reference class_center_sample, PartialFC):
    keep all positive classes plus uniformly sampled negatives; labels are
    remapped into the sampled index space."""
    import numpy as np

    y = np.asarray(_v(label)).reshape(-1)
    pos = np.unique(y)
    need = max(num_samples - pos.size, 0)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.RandomState(int(_random.next_key()[0]) % (2**31))
    neg = rng.choice(rest, size=min(need, rest.size), replace=False)
    sampled = np.sort(np.concatenate([pos, neg]))
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    new_y = np.asarray([remap[c] for c in y.tolist()], np.int64)
    return _t(jnp.asarray(new_y)), _t(jnp.asarray(sampled.astype(np.int64)))


# ------------------------------------------------------- misc structure

def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    from .layers_extra import PixelUnshuffle

    return PixelUnshuffle(downscale_factor, data_format)(x)


def channel_shuffle(x, groups, data_format="NCHW"):
    from .layers_extra import ChannelShuffle

    return ChannelShuffle(groups, data_format)(x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    from .layers_extra import Fold

    return Fold(output_sizes, kernel_sizes, strides, paddings, dilations)(x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """Shift a channel slice one frame forward/backward within each segment
    (reference temporal_shift_op: TSM)."""
    def fn(v):
        n, c, h, w = v.shape
        v5 = v.reshape(n // seg_num, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        back = jnp.concatenate(
            [v5[:, 1:, :fold_c], jnp.zeros_like(v5[:, :1, :fold_c])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v5[:, :1, fold_c:2 * fold_c]),
             v5[:, :-1, fold_c:2 * fold_c]], axis=1)
        keep = v5[:, :, 2 * fold_c:]
        return jnp.concatenate([back, fwd, keep], axis=2).reshape(n, c, h, w)

    return _dispatch(fn, x)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree): walk parent pointers
    from the last step to recover full beams. ids/parents: (T, B, W)."""
    i = _v(ids)
    p = _v(parents).astype(jnp.int32)
    T = i.shape[0]
    W = i.shape[-1]

    def step(carry, t):
        beam = carry  # (B, W) beam index selected at t+1
        sel = jnp.take_along_axis(i[t], beam, axis=-1)
        parent = jnp.take_along_axis(p[t], beam, axis=-1)
        return parent, sel

    init = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32), i.shape[1:])
    _, rows = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return _t(rows[::-1])


# ------------------------------------------------------------- attention

def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None):
    """Block-CSR sparse attention (reference sparse_attention GPU kernel).
    Reference semantics via a dense mask built from the CSR pattern — on
    TPU the masked softmax compiles to the same fused attention XLA
    emits; the CSR layout is honored, not the GPU kernel's schedule."""
    q, k, v = _v(query), _v(key), _v(value)
    offs = _v(sparse_csr_offset).astype(jnp.int32)
    cols = _v(sparse_csr_columns).astype(jnp.int32)
    seq = q.shape[-2]
    # dense allow-mask from the CSR pattern, built host-side (the pattern
    # is static per call)
    import numpy as np

    offs_np = np.asarray(offs).reshape(offs.shape[:-1] + (seq + 1,))
    cols_np = np.asarray(cols)
    mask = np.zeros(offs.shape[:-1] + (seq, seq), np.bool_)
    flat_off = offs_np.reshape(-1, seq + 1)
    flat_cols = cols_np.reshape(flat_off.shape[0], -1)
    flat_mask = mask.reshape(flat_off.shape[0], seq, seq)
    for b in range(flat_off.shape[0]):
        for r in range(seq):
            cs = flat_cols[b, flat_off[b, r]:flat_off[b, r + 1]]
            flat_mask[b, r, cs] = True
    mask = jnp.asarray(flat_mask.reshape(mask.shape))
    scale = q.shape[-1] ** -0.5

    def fn(qq, kk, vv):
        scores = jnp.einsum("...qd,...kd->...qk", qq, kk) * scale
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("...qk,...kd->...qd", probs, vv)

    return _dispatch(fn, query, key, value)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, *, training=True):
    """Packed-QKV flash attention (reference flash_attn_qkvpacked):
    qkv is (B, S, 3, H, D)."""
    from . import functional as F

    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return F.flash_attention(q, k, v, dropout=dropout, causal=causal,
                             training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False, *,
                                training=True):
    """Varlen packed flash attention: sequences concatenated along dim 0
    with cu_seqlens boundaries (reference flash_attn_varlen_qkvpacked).
    Each segment attends within itself.

    Served as ONE fused call: the packed buffer runs as a batch-1
    attention with per-token segment ids derived from cu_seqlens (the
    round-4 masked Pallas kernel path — within a segment, global causal
    equals local causal since positions are monotonic). Dropout, or a
    packed buffer extending past cu_seqlens[-1], falls back to the
    per-segment loop."""
    import math

    import numpy as np

    from ..core.tensor import Tensor
    from ..ops import scaled_dot_product_attention

    cu = np.asarray(_v(cu_seqlens_q)).astype(int)
    total = qkv.shape[0]
    d = qkv.shape[-1]
    # attention hard-codes 1/sqrt(d); a custom softmax scale folds into q
    # so logits come out scale * (q.k)
    q_all = (qkv[:, 0] * (float(scale) * math.sqrt(d))
             if scale is not None else qkv[:, 0])
    k_all = qkv[:, 1]
    v_all = qkv[:, 2]
    dropout_inert = dropout == 0.0 or not training
    # the fused path only pays off when the Pallas kernel serves it; an
    # unaligned total would fall to the dense XLA composition with
    # O(total^2) cross-segment logits — worse than the per-segment loop
    aligned = total % 128 == 0
    if (dropout_inert and aligned and len(cu) >= 2 and cu[0] == 0
            and cu[-1] == total):
        seg = np.zeros((1, total), np.int32)
        for i in range(len(cu) - 1):
            seg[0, cu[i]:cu[i + 1]] = i
        out = scaled_dot_product_attention(
            q_all.unsqueeze(0), k_all.unsqueeze(0), v_all.unsqueeze(0),
            is_causal=causal, segment_ids=Tensor._from_value(seg))
        return out.squeeze(0), None

    outs = []
    for i in range(len(cu) - 1):
        lo, hi = int(cu[i]), int(cu[i + 1])
        out = scaled_dot_product_attention(
            q_all[lo:hi].unsqueeze(0), k_all[lo:hi].unsqueeze(0),
            v_all[lo:hi].unsqueeze(0), is_causal=causal,
            dropout_p=dropout, training=training)
        outs.append(out.squeeze(0))
    from ..ops import concat

    return concat(outs, axis=0), None


def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False):
    """FlashMask attention (reference flashmask_attention): the sparse
    row-interval mask form; intervals become a dense additive mask here."""
    from . import functional as F

    if startend_row_indices is None:
        return F.flash_attention(query, key, value, dropout=dropout,
                                 causal=causal)[0], None
    q = _v(query)
    sq = q.shape[1]
    idx = _v(startend_row_indices).astype(jnp.int32)  # (B, H|1, Sk, 1)
    start = idx[..., 0]  # (B, H|1, Sk): query rows >= start[k] mask col k
    rows = jnp.arange(sq)[None, None, :, None]       # (1, 1, Sq, 1)
    mask = rows >= start[:, :, None, :]              # (B, H|1, Sq, Sk)
    add_mask = jnp.where(mask, -1e30, 0.0)
    from ..ops import scaled_dot_product_attention as sdpa

    out = sdpa(query, key, value,
               attn_mask=_t(add_mask.astype(q.dtype)),
               is_causal=causal)
    return out, None


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None):
    """Adaptive softmax (reference adaptive_log_softmax_with_loss): head
    classes + clustered tails with projected representations."""
    y = _v(label).astype(jnp.int32).reshape(-1)
    flat_tails = [w for pair in tail_weights for w in pair]

    def fn(x, hw, hb, *tails):
        head_logits = x @ hw
        if hb is not None:
            head_logits = head_logits + hb
        head_lp = jax.nn.log_softmax(head_logits, axis=-1)
        n_head = cutoffs[0]
        out = jnp.zeros(y.shape, x.dtype)
        in_head = y < n_head
        head_take = jnp.take_along_axis(
            head_lp, jnp.clip(y, 0, head_lp.shape[-1] - 1)[:, None],
            -1)[:, 0]
        out = jnp.where(in_head, head_take, out)
        for ci in range(len(cutoffs) - 1):
            lo, hi = cutoffs[ci], cutoffs[ci + 1]
            proj, cls_w = tails[2 * ci], tails[2 * ci + 1]
            h = x @ proj
            tail_lp = jax.nn.log_softmax(h @ cls_w, axis=-1)
            cluster_lp = head_lp[:, n_head + ci]
            rel = jnp.clip(y - lo, 0, tail_lp.shape[-1] - 1)
            take = jnp.take_along_axis(tail_lp, rel[:, None], -1)[:, 0]
            sel = (y >= lo) & (y < hi)
            out = jnp.where(sel, cluster_lp + take, out)
        return out, -jnp.mean(out)

    return _dispatch(fn, input, head_weight, head_bias, *flat_tails)
