"""Global flag registry.

The reference exposes ~184 runtime flags through its own gflags clone
(/root/reference/paddle/common/flags.cc, flags_native.cc) settable via env
vars and ``paddle.set_flags``. This is the same idea natively in Python:
flags are declared with defaults, overridable by ``FLAGS_*`` environment
variables at import and by ``set_flags`` at runtime.
"""
from __future__ import annotations

import os
from typing import Any

__all__ = ["define_flag", "set_flags", "get_flags", "flag"]

_FLAGS: dict[str, Any] = {}
_DOCS: dict[str, str] = {}


def _coerce(value, template):
    if isinstance(template, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(template, int):
        return int(value)
    if isinstance(template, float):
        return float(value)
    return value


def define_flag(name: str, default, doc: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    _FLAGS[name] = _coerce(env, default) if env is not None else default
    _DOCS[name] = doc
    return _FLAGS[name]


def set_flags(flags: dict):
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        if k not in _FLAGS:
            raise KeyError(f"Unknown flag {k}; declared flags: {sorted(_FLAGS)}")
        _FLAGS[k] = _coerce(v, _FLAGS[k])


def get_flags(flags=None):
    if flags is None:
        return dict(_FLAGS)
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        out[k] = _FLAGS[k]
    return out


def flag(name: str):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _FLAGS[name]


# Core flags (analogs of the reference's most-used ones).
define_flag("FLAGS_check_nan_inf", False, "Check outputs of every op for NaN/Inf")
define_flag("FLAGS_eager_op_jit", True, "Compile+cache per-op executables for eager mode")
define_flag("FLAGS_use_pallas_kernels", True, "Use Pallas kernels for fused ops when available")
define_flag("FLAGS_decode_megakernel", 1,
            "Fused per-layer Pallas decode step in serving (0 = off, "
            "1 = auto: Pallas megakernel on TPU / exact unfused "
            "composition on CPU, 2 = force the kernel in interpret "
            "mode off-TPU — tests and benches)")
define_flag("FLAGS_flash_attention_block_size", 256,
            "Preferred q/k block for the Pallas flash-attention kernel "
            "(256 measured fastest on v5e; falls back to 128 when the "
            "sequence is not divisible)")
define_flag("FLAGS_cross_host_device_put", False,
            "Cross-mesh pipeline: use native cross-host device_put (DCN; "
            "requires jax_cross_host_transfer_socket_address) instead of "
            "the coordination-KV host transport")
define_flag("FLAGS_default_dtype", "float32", "Default floating dtype for creation ops")
define_flag("FLAGS_retain_grad_for_all", False, "Retain .grad for non-leaf tensors")
define_flag("FLAGS_log_level", 0, "Framework VLOG level")
