"""KV-cache decode correctness + generate() API."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (
    LlamaForCausalLM,
    generate,
    llama_tiny_config,
)


def test_cached_decode_matches_full_forward():
    """Greedy decode with KV cache must pick the same tokens as rerunning the
    full sequence each step (RoPE offsets included)."""
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = np.random.randint(0, 256, (2, 8))

    # full-recompute greedy loop (oracle)
    cur = ids.copy()
    for _ in range(5):
        logits = model(paddle.to_tensor(cur))
        nxt = np.asarray(logits._value)[:, -1, :].argmax(-1)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)

    out = generate(model, paddle.to_tensor(ids), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out._value), cur)


def test_jit_decode_matches_eager_decode():
    """The compiled decode-loop program (prefill + scanned token steps in
    one executable, VERDICT r3 item 2) must pick exactly the tokens of the
    per-token eager loop, for both cache layouts."""
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 8)))
    for kind in ("static", "paged"):
        eager = generate(model, ids, max_new_tokens=6, cache=kind,
                         use_jit=False)
        jitted = generate(model, ids, max_new_tokens=6, cache=kind,
                          use_jit=True)
        np.testing.assert_array_equal(
            np.asarray(eager._value), np.asarray(jitted._value),
            err_msg=f"cache={kind}")


def test_jit_decode_sampling_rng_parity():
    """Sampling consumes the host RNG stream identically in both paths."""
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 5)))
    paddle.seed(42)
    eager = generate(model, ids, max_new_tokens=5, do_sample=True,
                     temperature=0.9, top_k=20, use_jit=False)
    paddle.seed(42)
    jitted = generate(model, ids, max_new_tokens=5, do_sample=True,
                      temperature=0.9, top_k=20, use_jit=True)
    np.testing.assert_array_equal(np.asarray(eager._value),
                                  np.asarray(jitted._value))


def test_jit_decode_eos_padding():
    """With an eos_token_id the jit path pads finished rows to full width."""
    paddle.seed(3)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 4)))
    # pick the greedy first token of row 0 as "eos" so it finishes at once
    probe = generate(model, ids, max_new_tokens=1, use_jit=True)
    eos = int(np.asarray(probe._value)[0, -1])
    out = np.asarray(generate(model, ids, max_new_tokens=6, eos_token_id=eos,
                              use_jit=True)._value)
    assert out.shape == (2, 10)
    assert (out[0, 4:] == eos).all()  # row 0 finished at token 0 -> padded


def test_jit_decode_program_cache_keys():
    """Cached decode programs must not leak a previous call's eos id or
    paged block tables (code-review r4 findings)."""
    paddle.seed(5)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 8)))
    # two different eos ids must behave like their eager counterparts
    for eos in (5, 77):
        jitted = np.asarray(generate(model, ids, max_new_tokens=4,
                                     eos_token_id=eos)._value)
        eager = np.asarray(generate(model, ids, max_new_tokens=4,
                                    eos_token_id=eos, use_jit=False)._value)
        w = eager.shape[1]
        np.testing.assert_array_equal(jitted[:, :w], eager, err_msg=f"eos={eos}")
    # paged path: a second call at a different batch/prompt shape must not
    # reuse the first call's block tables
    out1 = generate(model, paddle.to_tensor(
        np.random.randint(0, 256, (2, 8))), max_new_tokens=4, cache="paged")
    out2 = generate(model, paddle.to_tensor(
        np.random.randint(0, 256, (3, 16))), max_new_tokens=4, cache="paged")
    assert out1.shape == [2, 12] and out2.shape == [3, 20]


def test_generate_sampling_and_eos():
    paddle.seed(1)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (1, 4)))
    out = generate(model, ids, max_new_tokens=6, do_sample=True,
                   temperature=0.8, top_k=10)
    assert out.shape[1] == 10
    out2 = generate(model, ids, max_new_tokens=6, do_sample=True, top_p=0.9)
    assert out2.shape[1] == 10


def test_gpt_generate_matches_full_forward():
    """GPT decode caches (round 4): compiled generate() on GPTForCausalLM
    must pick the same tokens as full-sequence recompute, static AND paged
    caches."""
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny_config

    paddle.seed(0)
    model = GPTForCausalLM(gpt_tiny_config()).eval()
    ids = np.random.randint(0, 256, (2, 8))

    cur = ids.copy()
    for _ in range(5):
        logits = model(paddle.to_tensor(cur))
        nxt = np.asarray(logits._value)[:, -1, :].argmax(-1)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)

    for kind in ("static", "paged"):
        out = generate(model, paddle.to_tensor(ids), max_new_tokens=5,
                       cache=kind)
        np.testing.assert_array_equal(np.asarray(out._value), cur,
                                      err_msg=f"cache={kind}")
        eager = generate(model, paddle.to_tensor(ids), max_new_tokens=5,
                         cache=kind, use_jit=False)
        np.testing.assert_array_equal(np.asarray(eager._value), cur,
                                      err_msg=f"eager cache={kind}")


def test_generate_rejects_overflow_past_position_table():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config())  # max_pos=128
    model.train()
    ids = paddle.to_tensor(np.random.randint(0, 256, (1, 100)))
    with pytest.raises(ValueError, match="max_position_embeddings"):
        generate(model, ids, max_new_tokens=40)
    assert model.training  # refusal must not leak eval mode
    model.eval()
    # prompt exactly at the limit with ONE new token embeds only valid
    # positions (the sampled token is never fed back) — allowed
    full = paddle.to_tensor(np.random.randint(0, 256, (1, 128)))
    out = generate(model, full, max_new_tokens=1)
    assert out.shape == [1, 129]


def test_generate_zero_new_tokens_returns_input_unchanged():
    """max_new_tokens=0 is a no-op: (B, S + 0) = the input ids, no sample
    appended, no mode flip (advisor r4)."""
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config())
    model.train()
    ids = np.random.randint(0, 256, (2, 8))
    for kw in ({"use_jit": True}, {"use_jit": False}, {"cache": "paged"}):
        out = generate(model, paddle.to_tensor(ids), max_new_tokens=0, **kw)
        np.testing.assert_array_equal(np.asarray(out._value), ids)
    assert model.training  # no-op path must not leak eval mode


def test_generate_rejects_negative_new_tokens():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config())
    ids = paddle.to_tensor(np.random.randint(0, 256, (1, 4)))
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(model, ids, max_new_tokens=-1)
