"""Convolution and pooling layers.

Analogs of /root/reference/python/paddle/nn/layer/{conv.py,pooling.py}.
Weight layout [out_channels, in_channels/groups, *kernel] (reference OIHW);
XLA's layout assignment maps this onto the MXU without manual transposes.
"""
from __future__ import annotations

import math

from . import functional as F
from . import initializer as I
from .layer_base import Layer

__all__ = [
    "Conv1D",
    "Conv2D",
    "Conv3D",
    "Conv2DTranspose",
    "MaxPool1D",
    "MaxPool2D",
    "AvgPool1D",
    "AvgPool2D",
    "AdaptiveAvgPool2D",
    "AdaptiveMaxPool2D",
]


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        ndim,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        weight_attr=None,
        bias_attr=None,
        data_format="NCHW",
    ):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        fan_in = (in_channels // groups) * math.prod(self.kernel_size)
        w_shape = (out_channels, in_channels // groups) + self.kernel_size
        self.weight = self.create_parameter(
            w_shape,
            attr=weight_attr,
            default_initializer=I.Uniform(-1.0 / math.sqrt(fan_in), 1.0 / math.sqrt(fan_in)),
        )
        self.bias = self.create_parameter((out_channels,), attr=bias_attr, is_bias=True)

    def extra_repr(self):
        return (
            f"{self.in_channels}, {self.out_channels}, kernel_size={self.kernel_size}, "
            f"stride={self.stride}, padding={self.padding}"
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation, groups=self.groups)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation, groups=self.groups)


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.groups = groups
        fan_in = (in_channels // groups) * math.prod(kernel_size)
        # Transpose-conv weight layout [in_channels, out_channels/groups, kh, kw]
        # (reference convention).
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + tuple(kernel_size),
            attr=weight_attr,
            default_initializer=I.Uniform(-1.0 / math.sqrt(fan_in), 1.0 / math.sqrt(fan_in)),
        )
        self.bias = self.create_parameter((out_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.conv2d_transpose(
            x, self.weight, self.bias, stride=self.stride, padding=self.padding,
            output_padding=self.output_padding, dilation=self.dilation, groups=self.groups,
        )


# ------------------------------------------------------------ pooling


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def forward(self, x):
        return F.max_pool1d(x, kernel_size=self.kernel_size, stride=self.stride, padding=self.padding)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
                 data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def forward(self, x):
        return F.max_pool2d(x, kernel_size=self.kernel_size, stride=self.stride, padding=self.padding)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def forward(self, x):
        return F.avg_pool1d(x, kernel_size=self.kernel_size, stride=self.stride, padding=self.padding)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 divisor_override=None, data_format="NCHW", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding

    def forward(self, x):
        return F.avg_pool2d(x, kernel_size=self.kernel_size, stride=self.stride, padding=self.padding)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, output_size=self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, output_size=self.output_size)
