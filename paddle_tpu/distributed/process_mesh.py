"""ProcessMesh — an N-D cartesian arrangement of devices with named axes.

Analog of the reference's
/root/reference/paddle/phi/core/distributed/auto_parallel/process_mesh.h:34
and python/paddle/distributed/auto_parallel/process_mesh.py. The TPU-native
backing object is ``jax.sharding.Mesh``: mesh axis names become the names
used by ``PartitionSpec``/``NamedSharding`` and by in-program collectives
(``lax.psum(..., axis_name)``), which XLA lowers onto ICI/DCN.

Unlike the reference (one process per device, SPMD multi-process), jax is
single- or multi-controller: ``process_ids`` here index the global
``jax.devices()`` list rather than OS processes.
"""
from __future__ import annotations

import numpy as np

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "auto", "init_mesh"]


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh, dtype=np.int64)
        else:
            arr = np.asarray(process_ids, dtype=np.int64).reshape(shape)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} does not match mesh ndim {arr.ndim}"
            )
        if len(set(dim_names)) != len(dim_names):
            raise ValueError(f"duplicate dim_names {dim_names}")
        self._mesh = arr
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    # ------------------------------------------------ metadata

    @property
    def shape(self):
        return list(self._mesh.shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh.flatten().tolist()

    @property
    def mesh(self):
        return self._mesh

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh.shape[self._dim_names.index(dim_name)]

    def get_rank_by_dim_and_process_id(self, dim_name, process_id):
        axis = self._dim_names.index(dim_name)
        where = np.argwhere(self._mesh == process_id)
        if where.size == 0:
            return -1
        return int(where[0][axis])

    def __contains__(self, process_id: int):
        return bool((self._mesh == process_id).any())

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self._dim_names == other._dim_names
            and np.array_equal(self._mesh, other._mesh)
        )

    def __hash__(self):
        return hash((tuple(self._dim_names), self._mesh.tobytes(), self._mesh.shape))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    # ------------------------------------------------ jax backing

    def jax_mesh(self):
        """The backing ``jax.sharding.Mesh`` (built lazily: device discovery
        first touches the TPU runtime, which can take minutes on first
        contact — see VERDICT.md round-1 note)."""
        if self._jax_mesh is None:
            import jax
            from jax.sharding import Mesh

            devices = jax.devices()
            ids = self._mesh.flatten()
            if int(ids.max()) >= len(devices):
                raise RuntimeError(
                    f"ProcessMesh needs device id {int(ids.max())} but only "
                    f"{len(devices)} jax devices are visible; set "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count=N for "
                    f"virtual CPU meshes"
                )
            dev_arr = np.array([devices[i] for i in ids]).reshape(self._mesh.shape)
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    def get_group(self, dim_name=None):
        from .collective import Group

        if dim_name is None:
            return Group(ranks=self.process_ids, mesh=self, axis=None)
        return Group(ranks=self.process_ids, mesh=self, axis=dim_name)

    def get_mesh_with_dim(self, dim_name, index=None):
        """Sub-mesh: move ``dim_name`` to the front, optionally index into it
        (reference process_mesh.py get_mesh_with_dim)."""
        axis = self._dim_names.index(dim_name)
        order = [axis] + [i for i in range(self.ndim) if i != axis]
        new_mesh = self._mesh.transpose(order)
        new_names = [self._dim_names[i] for i in order]
        if index is None:
            return ProcessMesh(new_mesh, new_names)
        return ProcessMesh(new_mesh[index], new_names[1:])


_global_mesh: ProcessMesh | None = None


def set_mesh(mesh: ProcessMesh):
    """Install the global mesh (reference auto_parallel.set_mesh)."""
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> ProcessMesh | None:
    return _global_mesh


def init_mesh(dim_names=("dp",), shape=None):
    """Convenience: build a mesh over all visible devices and install it."""
    import jax

    n = len(jax.devices())
    if shape is None:
        shape = [n] + [1] * (len(dim_names) - 1)
    mesh = ProcessMesh(np.arange(n).reshape(shape), list(dim_names))
    set_mesh(mesh)
    return mesh


def auto(shape=None, dim_names=None):  # reference dist.auto placeholder
    return init_mesh(dim_names or ("dp",), shape)
