"""tpu-lint fixture: lock-discipline violations — a deliberate
lock-order inversion (deadlock), blocking calls under a lock, and an
attribute mutated both under and outside its class lock."""
import subprocess
import threading
import time


class Inverted:
    """m1 takes a then b; m2 takes b then a — the classic cycle."""

    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def m1(self):
        with self.lock_a:
            with self.lock_b:         # lock-order-cycle (a -> b)
                return 1

    def m2(self):
        with self.lock_b:
            with self.lock_a:         # lock-order-cycle (b -> a)
                return 2


class BlocksWhileLocked:
    def __init__(self, worker):
        self._lock = threading.Lock()
        self._worker = worker

    def stall(self):
        with self._lock:
            time.sleep(0.5)           # lock-blocking-call
            self._worker.join(1.0)    # lock-blocking-call
            subprocess.run(["true"])  # lock-blocking-call


class MixedMutation:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self.count = 0

    def locked_add(self, v):
        with self._lock:
            self._items.append(v)
            self.count += 1

    def racy_add(self, v):
        self._items.append(v)         # lock-mixed-mutation
        self.count += 1               # lock-mixed-mutation

    def _helper_under_lock(self):
        # called only under the lock -> inferred locked context, OK
        self._items.clear()

    def locked_reset(self):
        with self._lock:
            self._helper_under_lock()


class SelfDeadlock:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            with self._lock:          # lock-order-cycle (self-edge)
                return 0
