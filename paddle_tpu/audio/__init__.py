"""paddle_tpu.audio — audio feature extraction.

Analog of /root/reference/python/paddle/audio/ (features/layers.py:
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC; functional/: window
functions, mel scale conversions) built on the FFT op family — which on
TPU lowers to XLA's FFT.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = [
    "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC",
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "get_window", "create_dct",
    "backends", "features", "functional", "load", "save", "info",
]


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    f = np.asarray(freq, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                    mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, n_fft // 2 + 1)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    f_max = f_max or sr / 2
    fftfreqs = fft_frequencies(sr, n_fft)
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights *= enorm[:, None]
    return weights.astype(np.float32)


def get_window(window, win_length, fftbins=True):
    n = win_length
    if window == "hann":
        return np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    if window == "hamming":
        return np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    if window == "blackman":
        return np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    if window in ("rect", "boxcar", "ones"):
        return np.ones(n)
    raise ValueError(f"unsupported window {window!r}")


def create_dct(n_mfcc, n_mels, norm="ortho"):
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    return dct.T.astype(np.float32)  # (n_mels, n_mfcc)


def _frame(x, frame_length, hop_length):
    n = (x.shape[-1] - frame_length) // hop_length + 1
    idx = (np.arange(frame_length)[None, :]
           + hop_length * np.arange(n)[:, None])
    return x[..., idx]  # (..., n_frames, frame_length)


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad window
            pad = (n_fft - self.win_length) // 2
            w = np.pad(w, (pad, n_fft - self.win_length - pad))
        self.register_buffer("window", Tensor(w.astype(np.float32)),
                             persistable=False)

    def forward(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.center:
            pad = self.n_fft // 2
            mode = self.pad_mode if self.pad_mode != "reflect" else "reflect"
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)], mode=mode)
        frames = _frame(v, self.n_fft, self.hop_length)
        spec = jnp.fft.rfft(frames * self.window._value, axis=-1)
        mag = jnp.abs(spec) ** self.power
        return Tensor._from_value(jnp.swapaxes(mag, -1, -2))  # (..., freq, t)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32", **kwargs):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, **kwargs)
        fb = compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk, norm)
        self.register_buffer("fbank", Tensor(fb), persistable=False)

    def forward(self, x):
        spec = self.spectrogram(x)
        mel = jnp.einsum("mf,...ft->...mt", self.fbank._value, spec._value)
        return Tensor._from_value(mel)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, ref_value=1.0, amin=1e-10, top_db=None,
                 **kwargs):
        super().__init__()
        self.mel = MelSpectrogram(sr=sr, **kwargs)
        self.amin = amin
        self.ref_value = ref_value
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)._value
        log_spec = 10.0 * jnp.log10(jnp.maximum(m, self.amin))
        log_spec -= 10.0 * math.log10(max(self.ref_value, self.amin))
        if self.top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - self.top_db)
        return Tensor._from_value(log_spec)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_mels=64, **kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **kwargs)
        self.register_buffer("dct", Tensor(create_dct(n_mfcc, n_mels)),
                             persistable=False)

    def forward(self, x):
        lm = self.log_mel(x)._value
        out = jnp.einsum("mk,...mt->...kt", self.dct._value, lm)
        return Tensor._from_value(out)


from . import datasets  # noqa: E402,F401


# namespace parity: submodules + top-level WAV IO (reference audio exposes
# backends/features/functional and load/save/info at the package root)
from . import backends  # noqa: E402,F401
from . import features  # noqa: E402,F401
from . import functional  # noqa: E402,F401
from .backends import info, load, save  # noqa: E402,F401
