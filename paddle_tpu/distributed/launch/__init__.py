"""paddle_tpu.distributed.launch — the process launcher.

Analog of /root/reference/python/paddle/distributed/launch/ (main.py:23,
controllers/collective.py, controllers/master.py): rendezvous via a KV
master, rank/env assignment (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_MASTER), per-worker process spawn with log capture, a watch loop
that tears the job down on failure and (optionally) restarts it — the
reference's elastic controller behavior.

The KV master is the native TCPStore (paddle_tpu/native/tcp_store.cpp);
workers use it for barrier/endpoint exchange, mirroring HTTPMaster/
ETCDMaster. On TPU pods each *process* drives one host's chips
(multi-controller jax), so nproc_per_node maps to hosts-per-node rather
than chips.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "Pod"]


class Pod:
    """One node's worker processes (reference launch/job/pod.py)."""

    def __init__(self, nprocs, entry, entry_args, master_endpoint, log_dir=None,
                 env=None):
        self.nprocs = nprocs
        self.entry = entry
        self.entry_args = entry_args
        self.master_endpoint = master_endpoint
        self.log_dir = log_dir
        self.base_env = env or {}
        self.procs: list[subprocess.Popen] = []
        self.log_files = []

    def start(self):
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
        for rank in range(self.nprocs):
            env = dict(os.environ)
            env.update(self.base_env)
            # workers run with sys.path[0] = script dir; keep the launcher's
            # cwd importable (the reference launcher inherits it via cwd)
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in (os.getcwd(), env.get("PYTHONPATH", "")) if p)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(self.nprocs),
                "PADDLE_MASTER": self.master_endpoint,
                "PADDLE_RANK_IN_NODE": str(rank),
                "PADDLE_LOCAL_SIZE": str(self.nprocs),
            })
            cmd = [sys.executable, self.entry, *self.entry_args]
            if self.log_dir:
                log = open(os.path.join(self.log_dir, f"worker.{rank}.log"),
                           "w")
                self.log_files.append(log)
                proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=log)
            else:
                proc = subprocess.Popen(cmd, env=env)
            self.procs.append(proc)

    def poll(self):
        """None while running; else (rank, returncode) of first failure or
        (-1, 0) when all exited cleanly."""
        alive = False
        for rank, p in enumerate(self.procs):
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                return (rank, rc)
        return None if alive else (-1, 0)

    def stop(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(sig)
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.log_files:
            f.close()
        self.log_files.clear()


def launch(entry, entry_args=(), nproc_per_node=1, master=None, log_dir=None,
           max_restarts=0, env=None, elastic_np=None):
    """Run ``entry`` as ``nproc_per_node`` ranked worker processes.

    Returns 0 on success. Reference flow (launch/main.py → CollectiveController
    → Pod): start a TCPStore master, spawn ranked workers, watch; on worker
    failure stop the pod and (if restarts remain) relaunch everyone —
    elastic manager semantics (fleet/elastic/manager.py ElasticManager:125).

    ``elastic_np=(np_min, np_max)`` enables scale-in/out re-rendezvous
    (manager.py _update_fault_tolerance:457): after a worker failure the
    pod relaunches with the surviving worker count (clamped to np_min),
    each generation exported as ``PADDLE_ELASTIC_GENERATION``; a pending
    scale-out request (``request_scale_out``, e.g. from a recovered host)
    grows the next generation toward np_max.
    """
    from ..store import TCPStore

    store = None
    if master is None:
        store = TCPStore(is_master=True)
        master = f"127.0.0.1:{store.port}"

    restarts = 0
    nproc = nproc_per_node
    generation = 0
    scale_store = store  # client connection created lazily for external masters
    owns_scale_store = False
    try:
        while True:
            gen_env = dict(env or {})
            gen_env["PADDLE_ELASTIC_GENERATION"] = str(generation)
            pod = Pod(nproc, entry, list(entry_args), master,
                      log_dir=log_dir, env=gen_env)
            pod.start()
            while True:
                status = pod.poll()
                if status is None:
                    time.sleep(0.2)
                    continue
                rank, rc = status
                break
            if rc == 0:
                return 0
            survivors = sum(1 for p in pod.procs
                            if p.poll() in (None, 0))
            pod.stop()
            if restarts >= max_restarts:
                print(f"[launch] worker {rank} failed with code {rc}; "
                      f"no restarts left", file=sys.stderr)
                return rc
            restarts += 1
            generation += 1
            if elastic_np is not None:
                np_min, np_max = elastic_np
                if scale_store is None:
                    from ..store import TCPStore

                    try:
                        host, port = master.rsplit(":", 1)
                        scale_store = TCPStore(host=host, port=int(port),
                                               is_master=False, timeout=5)
                        owns_scale_store = True
                    except (ValueError, RuntimeError):
                        pass
                want = _pending_scale_out(scale_store)
                new_n = max(min(max(survivors, want), np_max), np_min)
                if new_n != nproc:
                    print(f"[launch] elastic re-rendezvous: world "
                          f"{nproc} -> {new_n} (generation {generation})",
                          file=sys.stderr)
                nproc = new_n
                if survivors < np_min and want == 0:
                    print(f"[launch] only {survivors} survivors < np_min "
                          f"{np_min}; relaunching at np_min", file=sys.stderr)
            print(f"[launch] worker {rank} failed (code {rc}); restart "
                  f"{restarts}/{max_restarts}", file=sys.stderr)
    finally:
        if owns_scale_store and scale_store is not None:
            scale_store.close()
        if store is not None:
            store.close()


def _pending_scale_out(store):
    """Consume a pending scale-out request (0 if none). Requests are posted
    with :func:`request_scale_out` against the job's master endpoint (the
    controller holds one client connection for the job's lifetime)."""
    if store is None:
        return 0
    n = store.add("launch/scale_out", 0)
    if n:
        # subtract EXACTLY the value read: the store's add is atomic, so a
        # request_scale_out racing in between survives (counter ends at
        # its posted value) and is consumed by the next generation
        store.add("launch/scale_out", -n)
    return n


def request_scale_out(store, target_world):
    """Ask the controller to grow the next generation to ``target_world``
    (the reference's host-rejoin path: a recovered node re-registers and
    the manager scales out at the next restart)."""
    store.add("launch/scale_out", int(target_world))
