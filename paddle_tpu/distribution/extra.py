"""Second tranche of distributions.

Analogs of /root/reference/python/paddle/distribution/{binomial,cauchy,
chi2,continuous_bernoulli,exponential_family,independent,lkj_cholesky,
multivariate_normal,student_t,transformed_distribution}.py — built on the
jnp/jax.random primitives rather than paddle kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core import random as _random
from . import Distribution, Gamma, _t, _v, register_kl
from .transform import Transform

__all__ = [
    "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
    "ExponentialFamily", "Independent", "LKJCholesky",
    "MultivariateNormal", "StudentT", "TransformedDistribution",
]


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _v(total_count)
        self.probs_ = _v(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs_.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.total_count * self.probs_,
                                   self.batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(
            self.total_count * self.probs_ * (1 - self.probs_),
            self.batch_shape))

    def sample(self, shape=()):
        key = _random.next_key()
        n = jnp.broadcast_to(self.total_count, self.batch_shape)
        p = jnp.broadcast_to(self.probs_, self.batch_shape)
        out = jax.random.binomial(key, n, p, tuple(shape) + self.batch_shape)
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        k = _v(value)
        n, p = self.total_count, jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        comb = (jax.lax.lgamma(n + 1.0) - jax.lax.lgamma(k + 1.0)
                - jax.lax.lgamma(n - k + 1.0))
        return _t(comb + k * jnp.log(p) + (n - k) * jnp.log1p(-p))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(self.loc + self.scale * jax.random.cauchy(
            key, tuple(shape) + self.batch_shape))

    rsample = sample

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(-math.log(math.pi) - jnp.log(self.scale) - jnp.log1p(z * z))

    def entropy(self):
        return _t(jnp.broadcast_to(
            math.log(4 * math.pi) + jnp.log(self.scale), self.batch_shape))

    def cdf(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(jnp.arctan(z) / math.pi + 0.5)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _v(df)
        self.df = df
        super().__init__(df / 2.0, jnp.asarray(0.5, df.dtype))


class ContinuousBernoulli(Distribution):
    """CB(λ): density C(λ) λ^x (1-λ)^{1-x} on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_ = _v(probs)
        self._lims = lims
        super().__init__(self.probs_.shape)

    def _log_const(self):
        lam = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        lo, hi = self._lims
        near_half = (lam > lo) & (lam < hi)
        safe = jnp.where(near_half, 0.25, lam)
        out = jnp.log(2.0 * jnp.abs(jnp.arctanh(1 - 2 * safe))
                      / jnp.abs(1 - 2 * safe))
        # Taylor expansion around 1/2: log C ≈ log 2 + 4(λ-1/2)^2/3
        taylor = math.log(2.0) + 4.0 / 3.0 * (lam - 0.5) ** 2
        return jnp.where(near_half, taylor, out)

    def log_prob(self, value):
        x = _v(value)
        lam = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        return _t(self._log_const() + x * jnp.log(lam)
                  + (1 - x) * jnp.log1p(-lam))

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        lam = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        lo, hi = self._lims
        near_half = (lam > lo) & (lam < hi)
        safe = jnp.where(near_half, 0.25, lam)
        icdf = (jnp.log1p(u * (2 * safe - 1) / (1 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return _t(jnp.where(near_half, u, icdf))

    rsample = sample

    @property
    def mean(self):
        lam = jnp.clip(self.probs_, 1e-6, 1 - 1e-6)
        lo, hi = self._lims
        near_half = (lam > lo) & (lam < hi)
        safe = jnp.where(near_half, 0.25, lam)
        out = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return _t(jnp.where(near_half, 0.5, out))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _v(df)
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        t = jax.random.t(key, self.df, tuple(shape) + self.batch_shape)
        return _t(self.loc + self.scale * t)

    rsample = sample

    def log_prob(self, value):
        df = self.df
        z = (_v(value) - self.loc) / self.scale
        lnorm = (jax.lax.lgamma((df + 1) / 2) - jax.lax.lgamma(df / 2)
                 - 0.5 * jnp.log(df * math.pi) - jnp.log(self.scale))
        return _t(lnorm - (df + 1) / 2 * jnp.log1p(z * z / df))

    @property
    def mean(self):
        return _t(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = self.scale ** 2 * self.df / (self.df - 2)
        return _t(jnp.where(self.df > 2, v, jnp.nan))


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _v(loc)
        if scale_tril is not None:
            self.scale_tril = _v(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = jnp.linalg.cholesky(_v(covariance_matrix))
        elif precision_matrix is not None:
            prec = _v(precision_matrix)
            self.scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        else:
            raise ValueError(
                "need covariance_matrix, precision_matrix or scale_tril")
        k = self.loc.shape[-1]
        batch = jnp.broadcast_shapes(
            self.loc.shape[:-1], self.scale_tril.shape[:-2])
        super().__init__(batch, (k,))

    @property
    def covariance_matrix(self):
        L = self.scale_tril
        return _t(L @ jnp.swapaxes(L, -1, -2))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape + self.event_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(jnp.sum(self.scale_tril ** 2, -1),
                                   self.batch_shape + self.event_shape))

    def sample(self, shape=()):
        key = _random.next_key()
        eps = jax.random.normal(
            key, tuple(shape) + self.batch_shape + self.event_shape)
        return _t(self.loc + jnp.einsum("...ij,...j->...i", self.scale_tril, eps))

    rsample = sample

    def _half_log_det(self):
        return jnp.sum(jnp.log(jnp.diagonal(self.scale_tril, axis1=-2,
                                            axis2=-1)), -1)

    def log_prob(self, value):
        k = self.event_shape[0]
        diff = _v(value) - self.loc
        L = jnp.broadcast_to(self.scale_tril, diff.shape[:-1] + (k, k))
        m = jax.scipy.linalg.solve_triangular(
            L, diff[..., None], lower=True)[..., 0]
        return _t(-0.5 * jnp.sum(m * m, -1) - self._half_log_det()
                  - 0.5 * k * math.log(2 * math.pi))

    def entropy(self):
        k = self.event_shape[0]
        return _t(jnp.broadcast_to(
            0.5 * k * (1 + math.log(2 * math.pi)) + self._half_log_det(),
            self.batch_shape))


class Independent(Distribution):
    """Reinterpret trailing batch dims of `base` as event dims."""

    def __init__(self, base, reinterpreted_batch_rank=1, name=None):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError("reinterpreted rank exceeds base batch rank")
        split = len(base.batch_shape) - self.rank
        super().__init__(base.batch_shape[:split],
                         base.batch_shape[split:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_event(self, x):
        return jnp.sum(_v(x), axis=tuple(range(-self.rank, 0))) \
            if self.rank else _v(x)

    def log_prob(self, value):
        return _t(self._sum_event(self.base.log_prob(value)))

    def entropy(self):
        return _t(self._sum_event(self.base.entropy()))


class TransformedDistribution(Distribution):
    """y = T(x), x ~ base; log p(y) = log p(x) - log|det J_T(x)|."""

    def __init__(self, base, transforms, name=None):
        from .transform import ChainTransform
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = (transforms[0] if len(transforms) == 1
                          else ChainTransform(transforms))
        # shape metadata follows the transform: probe the forward map and
        # split batch/event by the output event rank
        in_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        out = jax.eval_shape(self.transform._forward,
                             jax.ShapeDtypeStruct(in_shape, jnp.float32))
        event_rank = max(len(base.event_shape),
                         self.transform._codomain_event_rank)
        split = len(out.shape) - event_rank
        super().__init__(out.shape[:split], out.shape[split:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self.transform.forward(x)

    def log_prob(self, value):
        y = _v(value)
        x = self.transform._inverse(y)
        base_lp = _v(self.base.log_prob(_t(x)))
        ldj = self.transform._forward_log_det_jacobian(x)
        base_rank = len(self.base.event_shape)
        d = self.transform._domain_event_rank
        if d > base_rank:
            # transform promotes batch dims to event dims: reduce base_lp
            base_lp = jnp.sum(base_lp, axis=tuple(range(-(d - base_rank), 0)))
        elif d < base_rank:
            # elementwise transform under a multivariate base: reduce ldj
            ldj = jnp.sum(ldj, axis=tuple(range(-(base_rank - d), 0)))
        return _t(base_lp - ldj)


class ExponentialFamily(Distribution):
    """Natural-parameter family: log p(x|θ) = ⟨t(x), θ⟩ - A(θ) + h(x).

    Subclasses provide `_natural_parameters` (tuple of arrays) and
    `_log_normalizer(*theta)`; KL between two members of the same family
    follows from the Bregman divergence of A (computed with jax.grad),
    mirroring the reference's exponential_family.py entropy/KL route.
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *theta):
        raise NotImplementedError


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily(p, q):
    if type(p) is not type(q):
        raise NotImplementedError(
            "generic exponential-family KL needs matching families")
    tp = tuple(jnp.asarray(t, jnp.float32) for t in p._natural_parameters)
    tq = tuple(jnp.asarray(t, jnp.float32) for t in q._natural_parameters)
    # KL(p||q) = A(θq) - A(θp) - ⟨∇A(θp), θq - θp⟩, elementwise over batch
    # (grad of the summed log-normalizer is the elementwise derivative).
    grads = jax.grad(lambda *th: jnp.sum(p._log_normalizer(*th)),
                     argnums=tuple(range(len(tp))))(*tp)
    a_p = p._log_normalizer(*tp)
    a_q = q._log_normalizer(*tq)
    inner = sum(g * (b - a) for g, a, b in zip(grads, tp, tq))
    return _t(a_q - a_p - inner)


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices.

    Sampling uses the onion construction; the density over L is
    ∝ Π_{k=2..n} L_kk^{n-k+2η-2} with the normalizer derived from the
    per-row hemisphere integrals:
    log c = Σ_{k=2..n} [ ((k-1)/2)·log π − lgamma((k-1)/2)
                         + lbeta((k-1)/2, η + (n-k)/2) ].
    """

    def __init__(self, dim, concentration=1.0, name=None):
        self.dim = int(dim)
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape, (self.dim, self.dim))

    def sample(self, shape=()):
        n = self.dim
        eta = jnp.broadcast_to(self.concentration,
                               tuple(shape) + self.batch_shape)
        lead = eta.shape
        rows = [jnp.zeros(lead + (n,)).at[..., 0].set(1.0)]
        for i in range(1, n):
            kb, ku = _random.next_key(), _random.next_key()
            beta_b = eta + (n - 1 - i) / 2.0
            y = jax.random.beta(kb, i / 2.0, beta_b, lead)
            u = jax.random.normal(ku, lead + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            row = jnp.sqrt(y)[..., None] * u
            diag = jnp.sqrt(1.0 - y)
            pad = jnp.zeros(lead + (n - i - 1,))
            rows.append(jnp.concatenate([row, diag[..., None], pad], -1))
        return _t(jnp.stack(rows, -2))

    def log_prob(self, value):
        L = _v(value)
        n = self.dim
        eta = self.concentration
        diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
        k = jnp.arange(2, n + 1, dtype=diag.dtype)
        expo = n - k + 2 * eta[..., None] - 2
        unnorm = jnp.sum(expo * jnp.log(diag), -1)
        km1 = (k - 1) / 2.0
        b = eta[..., None] + (n - k) / 2.0
        # per-row normalizer (the lgamma(km1) of the hemisphere surface
        # measure cancels against the one inside log B(km1, b))
        log_c = jnp.sum(km1 * math.log(math.pi) + jax.lax.lgamma(b)
                        - jax.lax.lgamma(km1 + b), -1)
        return _t(unnorm - log_c)
