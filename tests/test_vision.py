"""vision: transforms, FakeData/parsers, model zoo forward/train.

Mirrors reference test/legacy_test/test_vision_models.py and
test/legacy_test/test_transforms.py behaviors.
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.vision import datasets, models, transforms as T


def test_transforms_pipeline():
    img = (np.random.rand(32, 32, 3) * 255).astype(np.uint8)
    tf = T.Compose([
        T.Resize(40), T.CenterCrop(32), T.RandomHorizontalFlip(0.0),
        T.ToTensor(), T.Normalize([0.5] * 3, [0.5] * 3),
    ])
    out = tf(img)
    assert out.shape == [3, 32, 32]
    v = np.asarray(out._value)
    assert v.min() >= -1.001 and v.max() <= 1.001


def test_resize_and_crop_shapes():
    img = np.zeros((10, 20, 3), np.uint8)
    assert T.resize(img, 5).shape[0] == 5  # short side
    assert T.resize(img, (7, 9)).shape[:2] == (7, 9)
    assert T.center_crop(img, 6).shape[:2] == (6, 6)
    assert T.pad(img, 2).shape[:2] == (14, 24)
    rc = T.RandomCrop(8)(img)
    assert rc.shape[:2] == (8, 8)


def test_fake_data_loader():
    ds = datasets.FakeData(num_samples=16, image_shape=(3, 8, 8))
    x, y = ds[3]
    assert x.shape == (3, 8, 8) and int(y) == 3
    batch = next(iter(DataLoader(ds, batch_size=8)))
    assert batch[0].shape == [8, 3, 8, 8]


def test_cifar10_parser(tmp_path):
    # build a miniature cifar-10 archive in the standard format
    data = {b"data": (np.random.rand(4, 3072) * 255).astype(np.uint8),
            b"labels": [0, 1, 2, 3]}
    tar_path = os.path.join(tmp_path, "cifar10.tar.gz")
    import io as _io

    with tarfile.open(tar_path, "w:gz") as tf:
        for name in ["data_batch_1", "test_batch"]:
            payload = pickle.dumps(data)
            info = tarfile.TarInfo(f"cifar-10-batches-py/{name}")
            info.size = len(payload)
            tf.addfile(info, _io.BytesIO(payload))
    ds = datasets.Cifar10(data_file=tar_path, mode="train")
    assert len(ds) == 4
    img, label = ds[1]
    assert img.shape == (32, 32, 3) and label == 1


def test_mnist_parser(tmp_path):
    imgs = (np.random.rand(3, 28, 28) * 255).astype(np.uint8)
    labels = np.array([1, 2, 3], np.uint8)
    ip = os.path.join(tmp_path, "img.gz")
    lp = os.path.join(tmp_path, "lab.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 3, 28, 28) + imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, 3) + labels.tobytes())
    ds = datasets.MNIST(image_path=ip, label_path=lp)
    assert len(ds) == 3
    img, label = ds[2]
    assert img.shape == (28, 28) and label == 3


def test_download_refused():
    with pytest.raises(RuntimeError, match="egress"):
        datasets.Cifar10(download=True)


@pytest.mark.parametrize("ctor,shape", [
    (models.LeNet, (2, 1, 28, 28)),
    (lambda: models.resnet18(num_classes=10), (2, 3, 32, 32)),
    (lambda: models.mobilenet_v2(num_classes=10), (2, 3, 32, 32)),
    (lambda: models.squeezenet1_1(num_classes=10), (2, 3, 64, 64)),
])
def test_model_forward(ctor, shape):
    model = ctor()
    x = paddle.to_tensor(np.random.rand(*shape).astype(np.float32))
    y = model(x)
    assert y.shape[0] == 2 and y.shape[-1] == 10


def test_resnet18_trains_on_fake_cifar():
    """BASELINE config 1 slice: resnet on synthetic CIFAR-shaped data."""
    paddle.seed(0)
    model = models.resnet18(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=model.parameters())
    crit = paddle.nn.CrossEntropyLoss()
    ds = datasets.FakeData(num_samples=32, image_shape=(3, 32, 32),
                           num_classes=10)
    loader = DataLoader(ds, batch_size=16)
    losses = []
    for _ in range(2):
        for x, y in loader:
            loss = crit(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_vgg11_forward():
    model = models.vgg11(num_classes=10)
    x = paddle.to_tensor(np.random.rand(1, 3, 224, 224).astype(np.float32))
    assert model(x).shape == [1, 10]


@pytest.mark.parametrize("ctor,size", [
    (lambda: models.densenet121(num_classes=10), 64),
    (lambda: models.googlenet(num_classes=10), 64),
    (lambda: models.inception_v3(num_classes=10), 96),
    (lambda: models.shufflenet_v2_x0_5(num_classes=10), 64),
    (lambda: models.mobilenet_v3_small(num_classes=10), 64),
    (lambda: models.mobilenet_v3_large(num_classes=10, scale=0.5), 64),
])
def test_more_model_zoo_forward(ctor, size):
    model = ctor()
    x = paddle.to_tensor(np.random.rand(1, 3, size, size).astype(np.float32))
    y = model(x)
    assert y.shape == [1, 10]
    y.sum().backward()
    grads = [p.grad is not None for p in model.parameters() if p.trainable]
    assert all(grads)


def test_color_and_geometry_transforms():
    import numpy as np

    from paddle_tpu.vision import transforms as T

    np.random.seed(0)
    img = (np.random.rand(32, 48, 3) * 255).astype(np.uint8)
    for t in [T.Grayscale(3), T.ColorJitter(0.4, 0.4, 0.4, 0.2),
              T.SaturationTransform(0.5), T.HueTransform(0.3),
              T.RandomRotation(30),
              T.RandomAffine(20, translate=(0.1, 0.1), scale=(0.8, 1.2),
                             shear=10),
              T.RandomPerspective(prob=1.0), T.RandomErasing(prob=1.0)]:
        out = t(img)
        assert out.shape == img.shape, type(t).__name__
        assert out.dtype == img.dtype, type(t).__name__
    # functional identities
    assert np.array_equal(T.rotate(img, 0), img)
    assert np.abs(T.adjust_hue(img, 0.0).astype(int)
                  - img.astype(int)).max() <= 2
    gray = T.to_grayscale(img, 1)
    assert np.allclose(T.to_grayscale(gray, 1), gray)
    # saturation 0 == grayscale
    assert np.abs(T.adjust_saturation(img, 0.0).astype(np.float32)
                  - T.to_grayscale(img, 3)).max() <= 1.0
    # erasing leaves some pixels changed and preserves dtype
    erased = T.RandomErasing(prob=1.0, value=0)(img)
    assert (erased != img).any()
