"""Ring attention (context parallelism) vs full attention, on the virtual
8-device mesh. This feature has no reference counterpart (SURVEY.md §5) —
correctness oracle is the dense computation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import ring_attention


def _dense(q, k, v, causal):
    b, s, h, d = q.shape
    qh, kh, vh = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    s_ = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        s_ = jnp.where(mask, s_, -jnp.inf)
    p = jax.nn.softmax(s_, axis=-1)
    return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [4, 8])
def test_matches_dense(causal, n):
    mesh = dist.ProcessMesh(np.arange(n), ["sp"])
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 8 * n, 2, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 8 * n, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 8 * n, 2, 16), jnp.float32)
    out = ring_attention(q, k, v, mesh, "sp", is_causal=causal)
    ref = _dense(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_output_stays_sequence_sharded():
    mesh = dist.ProcessMesh(np.arange(8), ["sp"])
    q = jnp.ones((1, 64, 2, 16), jnp.float32)
    out = ring_attention(q, q, q, mesh, "sp")
    # PartitionSpec equality over trailing Nones differs across jax
    # releases; compare the canonical (stripped) prefix instead
    spec = tuple(out.sharding.spec)
    assert spec[:2] == (None, "sp") and all(s is None for s in spec[2:])


def test_grad_matches_dense():
    mesh = dist.ProcessMesh(np.arange(4), ["sp"])
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 32, 2, 8), jnp.float32)

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh, "sp", is_causal=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (_dense(q, k, v, True) ** 2).sum()

    g_r = jax.grad(loss_ring, argnums=(0, 1, 2))(q, q, q)
    g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(q, q, q)
    for a, b in zip(g_r, g_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_eager_tensor_autograd():
    mesh = dist.ProcessMesh(np.arange(4), ["sp"])
    rng = np.random.RandomState(2)
    q = paddle.to_tensor(rng.randn(1, 32, 2, 8).astype(np.float32),
                         stop_gradient=False)
    out = ring_attention(q, q, q, mesh, "sp", is_causal=True)
    out.sum().backward()
    assert q.grad is not None
    assert np.isfinite(np.asarray(q.grad._value)).all()
