"""vision.transforms — image preprocessing.

Analog of /root/reference/python/paddle/vision/transforms/ (transforms.py +
functional.py). Numpy host-side preprocessing (runs in DataLoader workers);
images are HWC uint8/float ndarrays in, CHW float32 Tensors out of
``ToTensor`` — matching the reference's conventions.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Pad", "Transpose",
    "BrightnessTransform", "ContrastTransform", "RandomResizedCrop",
    "BaseTransform", "ColorJitter", "Grayscale", "HueTransform",
    "SaturationTransform", "RandomAffine", "RandomErasing",
    "RandomPerspective", "RandomRotation",
    "to_tensor", "normalize", "resize", "center_crop", "hflip", "vflip", "pad",
    "to_grayscale", "adjust_brightness", "adjust_saturation", "adjust_hue",
    "rotate",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    # bilinear via jax-free numpy sampling (nearest for 'nearest')
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    out = ((1 - wy) * (1 - wx) * f[y0[:, None], x0[None, :]]
           + (1 - wy) * wx * f[y0[:, None], x1[None, :]]
           + wy * (1 - wx) * f[y1[:, None], x0[None, :]]
           + wy * wx * f[y1[:, None], x1[None, :]])
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return img[i:i + th, j:j + tw]


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    if len(padding) == 2:
        padding = (padding[0], padding[1]) * 2
    pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)


def to_tensor(img, data_format="CHW"):
    from ..core.tensor import Tensor

    img = _as_hwc(img)
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from ..core.tensor import Tensor

    arr = np.asarray(img._value if isinstance(img, Tensor) else img,
                     dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.padding, self.pad_if_needed, self.fill = (
            size, padding, pad_if_needed, fill)

    def __call__(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill)
            h, w = img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.scale, self.ratio = size, scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return resize(img[i:i + th, j:j + tw], self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        img = _as_hwc(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        out = img.astype(np.float32) * alpha
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        img = _as_hwc(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        f = img.astype(np.float32)
        mean = f.mean()
        out = mean + alpha * (f - mean)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


# ------------------------------------------------- color / geometry tranche
# (reference transforms.py: ColorJitter, Grayscale, Hue/Saturation,
#  RandomRotation, RandomAffine, RandomPerspective, RandomErasing)


def to_grayscale(img, num_output_channels=1):
    orig_dtype = np.asarray(img).dtype
    img = _as_hwc(img).astype(np.float32)
    if img.shape[2] == 1:
        gray = img
    else:
        gray = (0.299 * img[..., 0] + 0.587 * img[..., 1]
                + 0.114 * img[..., 2])[..., None]
    out = np.repeat(gray, num_output_channels, axis=2)
    if orig_dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    return out


def adjust_brightness(img, factor):
    img = _as_hwc(img)
    out = img.astype(np.float32) * factor
    return np.clip(out, 0, 255 if img.dtype == np.uint8 else out.max()
                   ).astype(img.dtype)


def adjust_saturation(img, factor):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    gray = to_grayscale(f, 3)
    out = gray + factor * (f - gray)
    return np.clip(out, 0, 255 if img.dtype == np.uint8 else out.max()
                   ).astype(img.dtype)


def adjust_hue(img, factor):
    """factor in [-0.5, 0.5]: rotate hue via HSV round-trip."""
    img = _as_hwc(img)
    if img.shape[2] < 3:
        return img.copy()  # grayscale has no hue
    f = img.astype(np.float32)
    if img.dtype == np.uint8:
        f = f / 255.0
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = np.max(f, -1)
    minc = np.min(f, -1)
    v = maxc
    c = maxc - minc
    s = np.where(maxc > 0, c / np.maximum(maxc, 1e-12), 0.0)
    safe_c = np.maximum(c, 1e-12)
    hr = np.where((maxc == r), ((g - b) / safe_c) % 6, 0.0)
    hg = np.where((maxc == g) & (maxc != r), (b - r) / safe_c + 2, 0.0)
    hb = np.where((maxc == b) & (maxc != r) & (maxc != g),
                  (r - g) / safe_c + 4, 0.0)
    h = (hr + hg + hb) / 6.0
    h = (h + factor) % 1.0
    # hsv -> rgb
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - s * fr)
    t = v * (1 - s * (1 - fr))
    i = i.astype(np.int32) % 6
    rgb = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], -1)
    if img.dtype == np.uint8:
        rgb = np.clip(rgb * 255.0, 0, 255).astype(np.uint8)
    return rgb


def _sample_at(img, ys, xs, interpolation, fill):
    """Sample HWC img at float coords (out-of-bounds -> fill)."""
    h, w = img.shape[:2]
    shape = ys.shape + (img.shape[2],)
    if interpolation == "bilinear":
        valid = (ys >= 0) & (ys <= h - 1) & (xs >= 0) & (xs <= w - 1)
        y0 = np.clip(np.floor(ys), 0, h - 1).astype(int)
        x0 = np.clip(np.floor(xs), 0, w - 1).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[..., None]
        wx = (xs - x0)[..., None]
        f = img.astype(np.float32)
        out = ((1 - wy) * (1 - wx) * f[y0, x0] + (1 - wy) * wx * f[y0, x1]
               + wy * (1 - wx) * f[y1, x0] + wy * wx * f[y1, x1])
        out = np.where(valid[..., None], out, np.float32(fill))
        if img.dtype == np.uint8:
            out = np.clip(np.round(out), 0, 255).astype(np.uint8)
        else:
            out = out.astype(img.dtype)
        return out
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full(shape, fill, img.dtype)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by ``angle`` degrees. ``center`` is
    (x, y) — the paddle/PIL convention; ``expand=True`` enlarges the
    canvas to hold the whole rotated image (center override ignored then,
    as in PIL)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        nw = int(np.ceil(abs(w * cos) + abs(h * sin)))
        nh = int(np.ceil(abs(w * sin) + abs(h * cos)))
        cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
        ocx, ocy = (nw - 1) / 2.0, (nh - 1) / 2.0
    else:
        nw, nh = w, h
        if center is None:
            cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
        else:
            cx, cy = center
        ocx, ocy = cx, cy
    yy, xx = np.meshgrid(np.arange(nh), np.arange(nw), indexing="ij")
    dx = xx - ocx
    dy = yy - ocy
    xs = cos * dx - sin * dy + cx
    ys = sin * dx + cos * dy + cy
    return _sample_at(img, ys, xs, interpolation, fill)


def _affine_sample(img, matrix, interpolation="nearest", fill=0):
    """Inverse-map sampling with a 2x3 affine matrix over (x, y)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    xs = matrix[0, 0] * xx + matrix[0, 1] * yy + matrix[0, 2]
    ys = matrix[1, 0] * xx + matrix[1, 1] * yy + matrix[1, 2]
    return _sample_at(img, ys, xs, interpolation, fill)


class BaseTransform:
    """Reference transforms.py BaseTransform: keys-aware callable; the
    lean core treats every input as a single image."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, img):
        return self._apply_image(img)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        orig = np.asarray(img)
        out = to_grayscale(img, self.num_output_channels)
        return out.astype(orig.dtype) if orig.dtype == np.uint8 else out


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        f = 1.0 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        f = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, f)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order
    (reference transforms.py ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.brightness = float(brightness)
        self.contrast = float(contrast)
        self.saturation = float(saturation)
        self.hue = float(hue)

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = 1.0 + np.random.uniform(-self.brightness, self.brightness)
            ops.append(lambda im: adjust_brightness(im, f))
        if self.contrast:
            c = 1.0 + np.random.uniform(-self.contrast, self.contrast)

            def _contrast(im, c=c):
                m = im.astype(np.float32).mean()
                out = m + c * (im.astype(np.float32) - m)
                return np.clip(out, 0, 255 if im.dtype == np.uint8
                               else out.max()).astype(im.dtype)

            ops.append(_contrast)
        if self.saturation:
            s = 1.0 + np.random.uniform(-self.saturation, self.saturation)
            ops.append(lambda im: adjust_saturation(im, s))
        if self.hue:
            hf = np.random.uniform(-self.hue, self.hue)
            ops.append(lambda im: adjust_hue(im, hf))
        np.random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, interpolation=self.interpolation,
                      expand=self.expand, center=self.center, fill=self.fill)


class RandomAffine(BaseTransform):
    """Random rotation + translate + scale + shear via one inverse-mapped
    affine (reference transforms.py RandomAffine)."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        # reference shear forms: number -> x-shear range; [a, b] -> x-shear
        # range; [a, b, c, d] -> x and y ranges
        if shear is None:
            self.shear = None
        elif isinstance(shear, numbers.Number):
            self.shear = (-abs(shear), abs(shear), 0.0, 0.0)
        elif len(shear) == 2:
            self.shear = (shear[0], shear[1], 0.0, 0.0)
        elif len(shear) == 4:
            self.shear = tuple(shear)
        else:
            raise ValueError("shear must be a number or a 2/4-sequence")
        self.fill = fill
        self.center = center
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        angle = np.deg2rad(np.random.uniform(*self.degrees))
        sc = (np.random.uniform(*self.scale) if self.scale else 1.0)
        shx = shy = 0.0
        if self.shear is not None:
            shx = np.deg2rad(np.random.uniform(self.shear[0], self.shear[1]))
            shy = np.deg2rad(np.random.uniform(self.shear[2], self.shear[3]))
        tx = ty = 0.0
        if self.translate:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        if self.center is None:
            cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
        else:
            cx, cy = self.center  # (x, y), reference convention
        cos, sin = np.cos(angle), np.sin(angle)
        rot = np.array([[cos, -sin], [sin, cos]])
        shear_m = (np.array([[1.0, np.tan(shx)], [0.0, 1.0]])
                   @ np.array([[1.0, 0.0], [np.tan(shy), 1.0]]))
        lin = sc * (rot @ shear_m)
        fwd = np.eye(3)
        fwd[:2, :2] = lin
        pre = np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1.0]])
        post = np.array([[1, 0, cx + tx], [0, 1, cy + ty], [0, 0, 1.0]])
        m = post @ fwd @ pre
        inv = np.linalg.inv(m)[:2]
        return _affine_sample(img, inv, interpolation=self.interpolation,
                              fill=self.fill)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        if np.random.uniform() >= self.prob:
            return _as_hwc(img)
        img = _as_hwc(img)
        h, w = img.shape[:2]
        d = self.distortion_scale
        dx = int(d * w / 2)
        dy = int(d * h / 2)
        # displaced corners (x, y): tl tr br bl
        src = np.float32([[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]])
        dst = src + np.float32([
            [np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)],
            [-np.random.randint(0, dx + 1), np.random.randint(0, dy + 1)],
            [-np.random.randint(0, dx + 1), -np.random.randint(0, dy + 1)],
            [np.random.randint(0, dx + 1), -np.random.randint(0, dy + 1)],
        ])
        # homography dst -> src (inverse map) by DLT
        A = []
        for (x, y), (u, v) in zip(dst, src):
            A.append([x, y, 1, 0, 0, 0, -u * x, -u * y, -u])
            A.append([0, 0, 0, x, y, 1, -v * x, -v * y, -v])
        _, _, vt = np.linalg.svd(np.asarray(A, np.float64))
        Hm = vt[-1].reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        den = Hm[2, 0] * xx + Hm[2, 1] * yy + Hm[2, 2]
        xs = (Hm[0, 0] * xx + Hm[0, 1] * yy + Hm[0, 2]) / den
        ys = (Hm[1, 0] * xx + Hm[1, 1] * yy + Hm[1, 2]) / den
        return _sample_at(img, ys, xs, self.interpolation, self.fill)


class RandomErasing(BaseTransform):
    """Erase a random rectangle (reference transforms.py RandomErasing);
    operates on HWC arrays or CHW tensors alike by erasing along the two
    spatial dims inferred from the value layout."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        arr = np.array(img, copy=True)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] > 4
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0],
                                                         arr.shape[1])
        if np.random.uniform() >= self.prob:
            return arr
        area = h * w
        for _ in range(10):
            target = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                if chw:
                    arr[:, i:i + eh, j:j + ew] = self.value
                else:
                    arr[i:i + eh, j:j + ew] = self.value
                break
        return arr
