"""tpu-lint fixture: aliased wall-clock imports (wall-clock-alias)."""
import time as _t                     # -> rule: wall-clock-alias
from time import time                 # -> rule: wall-clock-alias


def now():
    return _t.time() + time()
