"""Sequence parallelism (Megatron-SP) utilities.

Analog of /root/reference/python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py (ScatterOp:85, GatherOp:97, AllGatherOp:111,
ReduceScatterOp:127, ColumnSequenceParallelLinear:429,
RowSequenceParallelLinear:564). The reference swaps the TP all-reduce pair
for all-gather (entering a TP block) + reduce-scatter (leaving it) along
the sequence dim. Under GSPMD the same exchange falls out of sharding
constraints: activations outside TP blocks are Shard(seq → mp); the
column-parallel matmul forces a gather, the row-parallel output is
constrained back to sequence-sharded so the Partial reduces via
reduce-scatter — exactly the Megatron-SP collective schedule, chosen by the
partitioner.

The shard_map-level primitives (hand-written collectives with custom VJPs)
live in distributed/comm_ops.py (all_gather/reduce_scatter/all_to_all).
"""
from __future__ import annotations

from ...nn.layer_base import Layer
from ..api import shard_constraint
from ..placement import Replicate, Shard
from ..process_mesh import get_mesh
from .mp_layers import ColumnParallelLinear, RowParallelLinear

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "scatter", "all_gather", "mark_as_sequence_parallel_parameter",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
    "register_sequence_parallel_allreduce_hooks",
]


def _seq_constraint(x, seq_dim, mp_axis="mp"):
    mesh = get_mesh()
    if mesh is None or mp_axis not in mesh.dim_names:
        return x
    pl = [Replicate()] * mesh.ndim
    pl[mesh.dim_names.index(mp_axis)] = Shard(seq_dim)
    return shard_constraint(x, mesh, pl)


def _replicate_constraint(x):
    mesh = get_mesh()
    if mesh is None:
        return x
    return shard_constraint(x, mesh, [Replicate()] * mesh.ndim)


def scatter(x, seq_dim=0):
    """Split the sequence dim across mp ranks (reference ScatterOp.forward:
    local slice; backward: all-gather). GSPMD derives both directions from
    the constraint."""
    return _seq_constraint(x, seq_dim)


def all_gather(x, seq_dim=0):
    """Gather sequence shards (GatherOp/AllGatherOp)."""
    return _replicate_constraint(x)


class ScatterOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return scatter(x, seq_dim)


class GatherOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return all_gather(x, seq_dim)


AllGatherOp = GatherOp


class ReduceScatterOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return _seq_constraint(x, seq_dim)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, *a, **k):
    """Reference registers grad all-reduce hooks for SP params (norms/biases
    whose grads are partial over the seq shards). GSPMD emits that reduction
    from the shardings; kept as a no-op for API parity."""
    return None


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """ColumnParallelLinear whose input arrives sequence-sharded
    (sequence_parallel_utils.py:429): the entering all-gather is implicit."""

    def forward(self, x):
        x = all_gather(x, seq_dim=max(x.ndim - 2, 0))
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """RowParallelLinear that leaves its output sequence-sharded
    (sequence_parallel_utils.py:564): Partial(mp) → Shard(seq) is a
    reduce-scatter, not an all-reduce."""

    def forward(self, x):
        from ...nn import functional as F

        y = F.linear(x, self.weight, None)
        y = _seq_constraint(y, max(y.ndim - 2, 0))
        if self.bias is not None:
            y = y + self.bias
        return y
