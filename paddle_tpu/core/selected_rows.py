"""Row-sparse gradient container.

Analog of the reference's SelectedRows (paddle/phi/core/selected_rows.h):
a tall dense tensor represented by the subset of touched rows — the
gradient type embedding lookups produce when ``sparse=True``, so a
V×D vocab table never materializes a dense V×D gradient. Optimizers apply
row-wise (lazy) updates (reference: paddle/phi/kernels/selected_rows/).

On TPU the dense scatter-add is what XLA compiles anyway inside jit; this
type exists for the eager path where V is large and the touched set is
small (host memory + dispatch win), and for API parity.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows: int32 [n]; value: [n, ...] per-row data; height: full dim 0."""

    def __init__(self, rows, value, height):
        self.rows = jnp.asarray(rows, jnp.int32)
        self.value = jnp.asarray(value)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    @property
    def dtype(self):
        return self.value.dtype

    def merged(self) -> "SelectedRows":
        """Coalesce duplicate rows (sum)."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True)
        merged = jnp.zeros((uniq.shape[0],) + self.value.shape[1:],
                           self.value.dtype).at[inv].add(self.value)
        return SelectedRows(uniq, merged, self.height)

    def to_dense(self):
        return jnp.zeros(self.shape, self.value.dtype).at[self.rows].add(
            self.value)

    def numpy(self):
        import numpy as np

        return np.asarray(self.to_dense())

    def astype(self, dtype):
        return SelectedRows(self.rows, self.value.astype(dtype), self.height)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.value, other.value]),
                self.height,
            )
        if isinstance(other, (jax.Array,)):
            return self.to_dense() + other
        return NotImplemented

    def __radd__(self, other):
        return self.__add__(other)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"nnz_rows={self.rows.shape[0]}, "
                f"row_shape={tuple(self.value.shape[1:])})")
