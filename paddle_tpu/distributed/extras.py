"""Long-tail `paddle.distributed` surface: enums, object collectives,
alltoall aliases, megatron `split`, sharding-stage markers, PS entry
configs, and gloo shims.

Analog of the reference's distributed `__all__` tail
(/root/reference/python/paddle/distributed/__init__.py): every name a
reference user can import resolves here to a TPU-native implementation or
an honest absorption shim. Collective semantics follow collective.py's
convention — single-controller arrays are already globally consistent;
multi-controller object movement rides the coordination-service KV (the
same host/DCN path as dist.send/recv).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .collective import (
    all_gather,
    all_to_all,
    barrier,
    get_rank,
    get_world_size,
)

__all__ = [
    "ParallelMode", "ReduceType", "DistAttr",
    "alltoall", "alltoall_single", "gather",
    "broadcast_object_list", "scatter_object_list",
    "get_backend", "is_available", "wait", "split", "shard_scaler",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
]


class ParallelMode:
    """Reference paddle.distributed.ParallelMode (parallel.py)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class ReduceType:
    """Semi-auto reduce types (reference auto_parallel ReduceType)."""

    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class DistAttr:
    """Lean TensorDistAttr surface (reference paddle.distributed.DistAttr:
    a (process_mesh, sharding_specs) pair). The TPU-native layout story is
    placements; this adapter converts specs ("x"/None per tensor dim) to
    them for APIs written against the reference type."""

    def __init__(self, mesh, sharding_specs):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs)

    def placements(self):
        from .placement import Replicate, Shard

        pl = [Replicate() for _ in range(self.process_mesh.ndim)]
        for tensor_dim, spec in enumerate(self.sharding_specs):
            if spec is None:
                continue
            pl[self.process_mesh.dim_names.index(spec)] = Shard(tensor_dim)
        return pl


# --------------------------------------------------------- collectives

def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """List-of-tensors all_to_all (reference communication/all_to_all.py
    alltoall): rank r's out[i] is rank i's in[r] — the exact alias of
    collective.all_to_all's surface."""
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all_to_all: split dim 0 into world equal (or given)
    chunks, exchange, concatenate."""
    n = max(get_world_size(group), 1)
    val = in_tensor._value if isinstance(in_tensor, Tensor) else in_tensor
    if in_split_sizes:
        idx = np.cumsum(in_split_sizes)[:-1]
        chunks = jnp.split(val, idx, axis=0)
    else:
        chunks = jnp.split(val, n, axis=0)
    outs: list = []
    all_to_all(outs, [Tensor._from_value(c) for c in chunks], group=group)
    result = jnp.concatenate([o._value for o in outs], axis=0)
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._value = result
        return out_tensor
    return Tensor._from_value(result)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Gather to ``dst`` (reference communication/gather.py): implemented
    as all_gather with non-dst ranks discarding — on TPU the all-gather
    rides the same ring the rooted gather would."""
    gathered = []
    all_gather(gathered, tensor, group=group)
    if gather_list is not None and get_rank(group) == dst:
        gather_list.clear()
        gather_list.extend(gathered)
    return gather_list


def broadcast_object_list(object_list, src=0, group=None):
    """Broadcast picklable objects (reference broadcast_object_list).
    Single-controller: already consistent. Multi-controller: the src
    publishes pickled payloads on the coordination-service KV (same
    transport as dist.send/recv; broadcast keys are read by many ranks so
    they are NOT consumed — they stay for the coordinator's lifetime,
    like the pipeline transport's)."""
    if jax.process_count() <= 1:
        return object_list
    import pickle

    from .collective import _kv_fetch, _kv_publish

    key = f"bcast_obj/{src}/{_obj_seq('b', src)}"
    if jax.process_index() == src:
        _kv_publish(key, pickle.dumps(object_list))
    else:
        got = pickle.loads(_kv_fetch(key, consume=False))
        object_list.clear()
        object_list.extend(got)
    return object_list


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter one picklable object per rank from ``src``; each rank
    consumes exactly its own key."""
    if jax.process_count() <= 1:
        out_object_list.clear()
        if in_object_list:
            out_object_list.append(in_object_list[0])
        return out_object_list
    import pickle

    from .collective import _kv_fetch, _kv_publish

    me = jax.process_index()
    seq = _obj_seq("s", src)
    if me == src:
        for r in range(jax.process_count()):
            _kv_publish(f"scatter_obj/{src}/{seq}/{r}",
                        pickle.dumps(in_object_list[r]))
    raw = _kv_fetch(f"scatter_obj/{src}/{seq}/{me}")
    out_object_list.clear()
    out_object_list.append(pickle.loads(raw))
    return out_object_list


_obj_seqs: dict = {}


def _obj_seq(kind, src):
    k = (kind, src)
    _obj_seqs[k] = _obj_seqs.get(k, 0) + 1
    return _obj_seqs[k] - 1


# --------------------------------------------------------- misc surface

def get_backend(group=None):
    """Reference get_backend() → the communication backend name; here the
    XLA collective runtime over the default jax platform."""
    return f"xla:{jax.default_backend()}"


def is_available():
    return True


def wait(tensor, group=None, use_calc_stream=True):
    """Reference wait(): stream synchronization. XLA's execution model has
    no user-visible streams — block on the value instead."""
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    try:
        v.block_until_ready()
    except AttributeError:
        pass
    return tensor


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style distributed fc/embedding (reference
    fleet/layers/mpu/mp_ops.py `paddle.distributed.split`): build the
    matching TP layer over the current mesh and apply it. ``operation``:
    "linear" (axis=0 row-parallel / axis=1 column-parallel) or
    "embedding" (vocab-parallel)."""
    from .fleet.mp_layers import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation != "linear":
        raise ValueError(f"split: unknown operation {operation!r}")
    if axis == 0:
        layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                  has_bias=bias_attr is not False)
    elif axis == 1:
        layer = ColumnParallelLinear(size[0], size[1],
                                     weight_attr=weight_attr,
                                     has_bias=bias_attr is not False,
                                     gather_output=gather_out)
    else:
        raise ValueError("split: axis must be 0 or 1 for linear")
    return layer(x)


def shard_scaler(scaler):
    """Reference shard_scaler(GradScaler): make unscale/found-inf work
    over sharded grads. Our GradScaler already reduces found-inf across
    the global mesh (XLA collectives), so this is the identity — kept for
    API parity."""
    return scaler


class _ShardingStage:
    def __init__(self, stage):
        self.stage = stage

    def __repr__(self):
        return f"ShardingStage{self.stage}"


ShardingStage1 = _ShardingStage(1)
ShardingStage2 = _ShardingStage(2)
ShardingStage3 = _ShardingStage(3)


# ------------------------------------------------ PS sparse-table entries

class _Entry:
    """Sparse-table admission policy config (reference
    distributed/entry_attr.py; consumed by the PS accessor)."""

    def _to_attr(self):
        raise NotImplementedError


class CountFilterEntry(_Entry):
    """Admit a sparse feature after ``count_filter`` occurrences."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self.count_filter}"


class ProbabilityEntry(_Entry):
    """Admit a sparse feature with the given probability."""

    def __init__(self, probability):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class ShowClickEntry(_Entry):
    """Weight features by show/click statistics (CTR accessors)."""

    def __init__(self, show_name, click_name):
        self.show_name = str(show_name)
        self.click_name = str(click_name)

    def _to_attr(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"


# --------------------------------------------------------- gloo shims

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Reference gloo CPU-barrier bootstrap. The TPU build's host control
    plane is the TCPStore + jax.distributed coordination service;
    init_parallel_env covers it — kept as a compatible entry point."""
    from .collective import init_parallel_env

    return init_parallel_env()


def gloo_barrier():
    barrier()


def gloo_release():
    return None
