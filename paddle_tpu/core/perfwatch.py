"""Performance observability over the PR 9 telemetry registry.

``core/telemetry.py`` answers "what is the fleet doing"; this layer
answers the PERFORMANCE questions the ROADMAP's open items need answered
in production before they can be attacked:

* **Step-time attribution** — where do a decode step's microseconds go?
  The serving engine observes every scheduler phase into ONE labeled
  histogram, ``serving.phase_s{phase=...}``:

  - ``prefill`` / ``chunked_prefill`` — admission dispatches (host prep
    + the synchronous first-token fetch, so device time is included);
  - ``segment_dispatch`` — host time to build and issue one compiled
    decode segment (async: the device keeps running after it returns);
  - ``device_wait`` — the blocking ``device_get`` when a segment's
    outputs are consumed (device compute not hidden by the pipeline);
  - ``host_bookkeeping`` — token collection / retirement;
  - ``host_gap`` — the between-segment host gap ``stats()['host_gap_ms']``
    already tracks, now with a full distribution.

  :func:`phase_summaries` renders p50/p95/p99 + mean per phase from the
  live registry or any (fleet-merged) snapshot — the measurement side of
  the decode-megakernel item (a fused kernel must beat the attributed
  ``segment_dispatch``+``device_wait`` budget, not a guess).

* **Memory watchdog** — :class:`MemoryWatchdog` polls
  ``paddle_tpu.device.memory_stats()`` (PJRT) into
  ``device.bytes_in_use`` / ``device.peak_bytes_in_use`` /
  ``device.bytes_limit`` gauges and fires a ``memory_hwm`` flight event
  (+ post-mortem dump, once per crossing with hysteresis) when usage
  crosses ``FLAGS_memory_hwm_pct`` of the limit. Backends without
  memory introspection (CPU) degrade GRACEFULLY: the gauges stay ABSENT
  — never zero/garbage — and
  ``perfwatch.memory_stats_unavailable`` counts the attempts. The
  engine adds the logical KV side (per-request bytes, slot occupancy,
  page fragmentation) in ``models/serving.py`` — the measurement side
  of the paged-KV item.

* **SLO monitor** — :class:`SLOMonitor` holds declared objectives
  (TTFT, per-token latency: a threshold in seconds + a target fraction)
  and computes rolling-window goodput and MULTI-WINDOW BURN RATE from
  the PR 9 serving histograms: each ``tick()`` snapshots the cumulative
  (total, good-within-threshold) pair per objective (good counts are
  interpolated from the histogram buckets at the threshold), and the
  burn rate over a window is ``error_rate / error_budget`` between the
  two snapshots bracketing it. The alarm flips when EVERY window burns
  above ``FLAGS_slo_burn_threshold`` (a short window alone is noise; a
  long window alone is too slow — the standard multi-window rule).
  ``ServingFrontend`` exposes the status in ``health()['slo']`` and —
  only behind ``FLAGS_slo_shedding`` — sheds admissions below
  ``FLAGS_slo_shed_below_priority`` while the alarm is up
  (``serving.slo_shed``); ``ServingRouter.fleet_metrics()['slo']``
  evaluates the same objectives over the fleet-merged histograms.

Everything here is default-on behind ``FLAGS_telemetry`` (the hot paths
observe only when ``telemetry.enabled()``); bench section (e6) gates the
whole layer's cost < 3% of active processing, same A/B methodology as
PR 9's e5.
"""
from __future__ import annotations

import threading
import time

from . import telemetry
from .flags import define_flag, flag

__all__ = [
    "observe_phase", "phase_summaries", "PHASES",
    "MemoryWatchdog", "memory_watchdog",
    "SLOMonitor", "Objective", "default_objectives",
]

define_flag("FLAGS_memory_hwm_pct", 90.0,
            "Device-memory high watermark (% of bytes_limit) past which "
            "the memory watchdog records a memory_hwm flight event and "
            "dumps the flight recorder (once per crossing; re-arms when "
            "usage falls below ~80% of the watermark)")
define_flag("FLAGS_memory_poll_interval_s", 0.5,
            "Min seconds between device.memory_stats() polls on the "
            "serving path (maybe_poll rate limit)")
define_flag("FLAGS_slo_ttft_s", 1.0,
            "TTFT objective threshold (seconds) for the SLO monitor")
define_flag("FLAGS_slo_token_s", 0.25,
            "Per-token decode-latency objective threshold (seconds)")
define_flag("FLAGS_slo_target", 0.99,
            "SLO target fraction: this share of requests must land "
            "within the objective threshold (error budget = 1 - target)")
define_flag("FLAGS_slo_windows", "30,300",
            "Comma-separated burn-rate window lengths in seconds, "
            "shortest first (multi-window alarm: ALL must burn)")
define_flag("FLAGS_slo_burn_threshold", 2.0,
            "Burn-rate alarm threshold: error_rate/error_budget above "
            "this on EVERY window flips the alarm")
define_flag("FLAGS_slo_shedding", False,
            "When the SLO burn alarm is up, shed frontend admissions "
            "below FLAGS_slo_shed_below_priority (default OFF: the "
            "monitor observes; shedding is an explicit operator opt-in)")
define_flag("FLAGS_slo_shed_below_priority", 1,
            "Admissions with priority strictly below this are shed "
            "while the burn alarm is up (with FLAGS_slo_shedding on)")

# ------------------------------------------------------ phase attribution

PHASES = ("prefill", "chunked_prefill", "segment_dispatch", "device_wait",
          "host_bookkeeping", "host_gap")

# phase durations span ~10us (a pipelined dispatch) to seconds (a cold
# chunked prefill): finer-than-default low end
_PHASE_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                  5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

_M_PHASE = telemetry.histogram(
    "serving.phase_s", "engine scheduler time by phase (prefill / "
    "chunked_prefill / segment_dispatch / device_wait / "
    "host_bookkeeping / host_gap) — see core/perfwatch.py for the "
    "device-vs-host semantics of each label", buckets=_PHASE_BUCKETS)


def observe_phase(phase, dur_s):
    """One phase observation (callers gate on ``telemetry.enabled()``)."""
    _M_PHASE.observe(dur_s, phase=phase)


def phase_summaries(snapshot=None) -> dict:
    """Per-phase p50/p95/p99 + count/mean (seconds) from the live
    registry, or from a (possibly fleet-merged) snapshot dict. Phases
    nobody observed are absent."""
    out = {}
    if snapshot is None:
        for key in _M_PHASE.series():
            phase = dict(key).get("phase")
            if phase is not None:
                out[phase] = _M_PHASE.summary(phase=phase)
        return out
    prefix = "serving.phase_s{"
    for name in (snapshot.get("histograms") or {}):
        if not name.startswith(prefix):
            continue
        labels = dict(p.split("=", 1)
                      for p in name[len(prefix):-1].split(","))
        phase = labels.get("phase")
        if phase is not None:
            out[phase] = telemetry.summary_from_snapshot(snapshot, name)
    return out


# -------------------------------------------------------- memory watchdog

_M_MEM_USE = telemetry.gauge(
    "device.bytes_in_use", "PJRT allocator bytes in use (absent on "
    "backends without memory_stats)")
_M_MEM_PEAK = telemetry.gauge(
    "device.peak_bytes_in_use", "PJRT allocator peak bytes in use")
_M_MEM_LIMIT = telemetry.gauge(
    "device.bytes_limit", "PJRT allocator capacity")
_M_MEM_UNAVAIL = telemetry.counter(
    "perfwatch.memory_stats_unavailable", "memory_stats() polls that "
    "returned nothing (CPU backends) — the gauges stay absent")


class MemoryWatchdog:
    """Poll PJRT memory stats into gauges + a high-watermark flight
    event. One instance per process is enough (``memory_watchdog()``);
    ``maybe_poll()`` rate-limits itself so hot loops can call it
    unconditionally."""

    def __init__(self, device_id=0, hwm_pct=None, min_interval_s=None):
        self.device_id = int(device_id)
        self._hwm_pct = hwm_pct
        self._interval = min_interval_s
        self._lock = threading.Lock()
        self._last_poll = None
        self._hwm_fired = False
        self.available = None  # unknown until the first poll

    def poll(self):
        """One ``device.memory_stats()`` read. Returns the stats dict,
        or None when the backend exposes none — in which case the gauges
        are left ABSENT (a dashboard must read "no data", not "0 bytes
        on a 16GB chip")."""
        from .. import device as _device

        self._last_poll = time.monotonic()
        try:
            stats = _device.memory_stats(self.device_id) or {}
        except Exception:  # noqa: BLE001 — introspection must never
            # take down the serving path it watches
            stats = {}
        in_use = stats.get("bytes_in_use")
        if in_use is None:
            self.available = False
            _M_MEM_UNAVAIL.inc()
            return None
        self.available = True
        _M_MEM_USE.set(int(in_use))
        peak = stats.get("peak_bytes_in_use")
        if peak is not None:
            _M_MEM_PEAK.set(int(peak))
        limit = stats.get("bytes_limit")
        if limit:
            _M_MEM_LIMIT.set(int(limit))
            self._check_hwm(int(in_use), int(limit))
        return stats

    def maybe_poll(self):
        """Rate-limited :meth:`poll` for per-step call sites."""
        interval = (self._interval if self._interval is not None
                    else float(flag("FLAGS_memory_poll_interval_s")))
        with self._lock:
            now = time.monotonic()
            if (self._last_poll is not None
                    and now - self._last_poll < interval):
                return None
            self._last_poll = now
        return self.poll()

    def _check_hwm(self, in_use, limit):
        hwm = (self._hwm_pct if self._hwm_pct is not None
               else float(flag("FLAGS_memory_hwm_pct"))) / 100.0
        pct = in_use / limit
        if pct >= hwm:
            if not self._hwm_fired:
                self._hwm_fired = True
                telemetry.flight_dump(
                    "memory_hwm", device=self.device_id,
                    bytes_in_use=in_use, bytes_limit=limit,
                    pct=round(100.0 * pct, 1))
        elif pct < hwm * 0.8:
            # hysteresis: don't re-dump on every oscillation around the
            # watermark, but a real second incident after recovery fires
            self._hwm_fired = False


_memwatch = MemoryWatchdog()


def memory_watchdog() -> MemoryWatchdog:
    return _memwatch


# ------------------------------------------------------------ SLO monitor

class Objective:
    """One declared latency objective: ``target`` fraction of samples of
    histogram ``hist`` must land within ``threshold_s``."""

    __slots__ = ("name", "hist", "threshold_s", "target")

    def __init__(self, name, hist, threshold_s, target):
        self.name = str(name)
        self.hist = str(hist)
        self.threshold_s = float(threshold_s)
        self.target = float(target)
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target}")


def default_objectives() -> list:
    """The declared serving objectives, from flags: TTFT and per-token
    decode latency over the PR 9 histograms."""
    target = float(flag("FLAGS_slo_target"))
    return [
        Objective("ttft", "serving.ttft_s",
                  flag("FLAGS_slo_ttft_s"), target),
        Objective("token_latency", "serving.token_latency_s",
                  flag("FLAGS_slo_token_s"), target),
    ]


def _count_within(row, threshold) -> float:
    """Samples <= threshold estimated from one histogram series row
    (``{count, bounds, buckets, sample}``) — cumulative finite buckets
    with linear interpolation inside the crossing bucket; the +inf
    bucket never counts as good. When the buckets are gone (a
    bounds-mismatched ``merge_snapshots`` invalidates them to None —
    mixed code versions in a rolling fleet), the merged RESERVOIR
    estimates the good fraction instead: reading a healthy fleet as
    0% goodput would flip a false burn alarm, the exact garbage-output
    case the merge hardening exists to prevent."""
    bounds = row.get("bounds") or ()
    buckets = row.get("buckets")
    if not bounds or not buckets:
        sample = row.get("sample") or ()
        if sample:
            frac = sum(1 for v in sample if v <= threshold) / len(sample)
            return float(row.get("count", 0)) * frac
        return 0.0
    acc = 0.0
    lo = 0.0
    for i, b in enumerate(bounds):
        c = buckets[i]
        if b <= threshold:
            acc += c
            lo = b
            continue
        if threshold > lo and b > lo:
            acc += c * (threshold - lo) / (b - lo)
        return acc
    return acc


class SLOMonitor:
    """Rolling-window goodput + multi-window burn rate over the serving
    latency histograms.

    ``tick(now)`` appends one cumulative ``(now, total, good)`` snapshot
    per objective (reading the process registry, or ``source()`` — a
    fleet-merged snapshot provider). ``status(now)`` computes, per
    objective and per window, the delta between the snapshot bracketing
    the window start and now:

    * ``goodput`` = good/total over the window (1.0 when idle — no
      traffic burns no budget);
    * ``burn`` = (1 - goodput) / (1 - target): 1.0 means errors arrive
      exactly at the budgeted rate; the alarm threshold (default 2.0)
      means the budget is burning at least twice too fast.

    The ALARM requires every window above threshold with at least
    ``min_count`` samples in the shortest one — a single slow request
    in an idle second must not shed traffic. Time is monotonic;
    ``now=`` overrides exist for deterministic drills."""

    def __init__(self, objectives=None, windows=None, burn_threshold=None,
                 min_count=8, source=None, shed_below=None):
        self.objectives = (list(objectives) if objectives is not None
                           else default_objectives())
        self._windows = windows
        self._burn_threshold = burn_threshold
        self.min_count = int(min_count)
        self._source = source
        self._shed_below = shed_below
        self._lock = threading.Lock()
        self._samples: dict[str, list] = {o.name: []
                                          for o in self.objectives}
        self._alarm = False
        self._status_cache = None   # (monotonic ts, status dict)

    def windows(self) -> tuple:
        if self._windows is not None:
            return tuple(self._windows)
        return tuple(sorted(float(w) for w in
                            str(flag("FLAGS_slo_windows")).split(",") if w))

    def burn_threshold(self) -> float:
        return (float(self._burn_threshold)
                if self._burn_threshold is not None
                else float(flag("FLAGS_slo_burn_threshold")))

    # ------------------------------------------------------------ ticking

    def _row(self, obj):
        """Cumulative (total, good) for one objective right now."""
        if self._source is not None:
            snap = self._source() or {}
            row = (snap.get("histograms") or {}).get(obj.hist)
        else:
            row = telemetry.histogram(obj.hist).snapshot_series().get(())
        if not row or not row.get("count"):
            return 0, 0.0
        return int(row["count"]), _count_within(row, obj.threshold_s)

    def tick(self, now=None):
        """Record one cumulative snapshot per objective and prune
        samples older than twice the longest window. Auto-clocked ticks
        (``now=None`` — health polls, pump turns) rate-limit themselves
        to ~10 per shortest window so a hot poll loop cannot grow the
        sample rings; an explicit ``now`` always records (drills)."""
        windows = self.windows()
        if now is None:
            now = time.monotonic()
            interval = max(min(windows) / 10.0, 0.05) if windows else 1.0
            with self._lock:
                rows = next(iter(self._samples.values()), None)
                if rows and now - rows[-1][0] < interval:
                    return
        else:
            now = float(now)
        horizon = now - 2.0 * (max(windows) if windows else 300.0)
        with self._lock:
            for obj in self.objectives:
                total, good = self._row(obj)
                rows = self._samples[obj.name]
                rows.append((now, total, good))
                while len(rows) > 1 and rows[0][0] < horizon:
                    rows.pop(0)

    # ------------------------------------------------------------- status

    def _window_delta(self, rows, now, window):
        """(d_total, d_good) between the newest snapshot at or before
        ``now - window`` (falling back to the oldest) and the latest."""
        if len(rows) < 2:
            return 0, 0.0
        cut = now - window
        base = rows[0]
        for r in rows:
            if r[0] <= cut:
                base = r
            else:
                break
        last = rows[-1]
        return max(last[1] - base[1], 0), max(last[2] - base[2], 0.0)

    def status(self, now=None) -> dict:
        """Tick, then evaluate every objective; updates the cached alarm
        :meth:`should_shed` reads. Plain ints/floats/bools — the dict
        rides ``health()`` across the RPC wire."""
        # auto-clocked calls (health polls, every pump turn) are served
        # from a short-lived cache on the tick cadence: the burn rate
        # only moves when a tick lands, and a hot pump loop must not pay
        # a full evaluation per step. Explicit ``now`` (drills) always
        # evaluates.
        if now is None:
            windows = self.windows()
            ttl = max(min(windows) / 10.0, 0.05) if windows else 1.0
            cached = self._status_cache
            t = time.monotonic()
            if cached is not None and t - cached[0] < ttl:
                return cached[1]
        # tick BEFORE resolving now: an auto-clocked call must keep the
        # tick's rate limiter engaged — appending (and then scanning) a
        # sample row per pump turn would grow without the traffic moving
        self.tick(now)
        now = time.monotonic() if now is None else float(now)
        threshold = self.burn_threshold()
        windows = self.windows()
        out = {"alarm": False, "burn_threshold": threshold,
               "windows_s": list(windows), "objectives": {}}
        any_alarm = False
        with self._lock:
            for obj in self.objectives:
                rows = self._samples[obj.name]
                burns = {}
                goodputs = {}
                counts = {}
                obj_alarm = len(windows) > 0
                for w in windows:
                    d_total, d_good = self._window_delta(rows, now, w)
                    key = f"{w:g}s"
                    counts[key] = d_total
                    if d_total <= 0:
                        goodputs[key] = 1.0
                        burns[key] = 0.0
                        obj_alarm = False
                        continue
                    gp = min(d_good / d_total, 1.0)
                    goodputs[key] = gp
                    burns[key] = (1.0 - gp) / max(1.0 - obj.target, 1e-9)
                    if burns[key] <= threshold:
                        obj_alarm = False
                # volume floor on the SHORTEST window: a single slow
                # request in an idle second is not an incident
                if (windows and counts.get(f"{min(windows):g}s", 0)
                        < self.min_count):
                    obj_alarm = False
                out["objectives"][obj.name] = {
                    "hist": obj.hist,
                    "threshold_s": obj.threshold_s,
                    "target": obj.target,
                    "goodput": goodputs,
                    "burn": burns,
                    "window_count": counts,
                    "alarm": obj_alarm,
                }
                any_alarm = any_alarm or obj_alarm
            self._alarm = any_alarm
        out["alarm"] = any_alarm
        self._status_cache = (time.monotonic(), out)
        return out

    def alarm(self) -> bool:
        """Cached verdict of the last :meth:`status` evaluation."""
        with self._lock:
            return self._alarm

    def should_shed(self, priority) -> bool:
        """True when burn-rate shedding is ON (``FLAGS_slo_shedding``),
        the alarm is up, and the admission's priority is below the
        protected class — the frontend's pre-queue check."""
        if not flag("FLAGS_slo_shedding") or not self.alarm():
            return False
        below = (self._shed_below if self._shed_below is not None
                 else int(flag("FLAGS_slo_shed_below_priority")))
        return int(priority) < below
