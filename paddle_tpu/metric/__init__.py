"""paddle_tpu.metric — evaluation metrics.

Analog of /root/reference/python/paddle/metric/metrics.py
(Metric, Accuracy, Precision, Recall, Auc).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self._name

    def compute(self, pred, label, *args):
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (reference metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        if label.ndim == pred.ndim and label.shape[-1] > 1:  # one-hot
            label = label.argmax(-1)
        label = label.reshape(label.shape[0], -1)
        idx = np.argsort(-pred, axis=-1)[:, : self.maxk]
        correct = (idx == label[:, :1]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _np(correct)
        for i, k in enumerate(self.topk):
            num = correct[:, :k].sum()
            self.total[i] += num
            self.count[i] += correct.shape[0]
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = (self.total / np.maximum(self.count, 1)).tolist()
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).flatten()
        labels = _np(labels).astype(np.int64).flatten()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).flatten()
        labels = _np(labels).astype(np.int64).flatten()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(Metric):
    """ROC AUC via threshold buckets (reference metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.flatten()
        labels = _np(labels).flatten()
        buckets = np.round(preds * self.num_thresholds).astype(np.int64)
        buckets = np.clip(buckets, 0, self.num_thresholds)
        for b, l in zip(buckets, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over descending thresholds
        area = 0.0
        pos = neg = 0.0
        for b in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[b], self._stat_neg[b]
            area += n * (pos + p / 2)
            pos += p
            neg += n
        return area / (tot_pos * tot_neg)


def accuracy(input, label, k=1):
    """Functional top-k accuracy (reference paddle.metric.accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1, 1)
    idx = np.argsort(-pred, axis=-1)[:, :k]
    correct = (idx == lab).any(axis=1).astype(np.float32)
    return Tensor(np.asarray(correct.mean(), np.float32))
