"""Export trained models to StableHLO artifacts and serve them through the
Predictor — including the REAL serving artifact: a LLaMA compiled decode
loop (prefill + scanned decode + sampling in one program, paged KV caches)
exported bf16 and driven through the inference.Config/Predictor surface.

Run: python examples/export_and_serve.py [--cpu]
"""
import sys
import tempfile

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static import InputSpec

# ---- 1. plain layer artifact -------------------------------------------
paddle.seed(0)
model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 4))
path = tempfile.mkdtemp() + "/model"
paddle.jit.save(model, path, input_spec=[InputSpec([1, 16], "float32")])
print("exported StableHLO artifact:", path + ".pdmodel")

predictor = inference.create_predictor(inference.Config(path))
x = np.random.rand(1, 16).astype(np.float32)
predictor.get_input_handle(predictor.get_input_names()[0]).copy_from_cpu(x)
(out,) = predictor.run()
print("served output:", out)

# ---- 2. LLaMA compiled-decode artifact, served bf16 --------------------
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

B, S, NEW = 2, 8, 12
cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                  num_hidden_layers=2, num_attention_heads=4,
                  max_position_embeddings=64, tie_word_embeddings=True)
paddle.seed(0)
llama = LlamaForCausalLM(cfg)
llama.to(dtype="bfloat16")  # export a true-bf16 program (TPU serving dtype)
lpath = tempfile.mkdtemp() + "/llama_decode"
paddle.jit.save_generate(llama, lpath, batch=B, prompt_len=S,
                         max_new_tokens=NEW, do_sample=True, temperature=0.8,
                         top_k=20, cache="paged")
print("exported compiled-decode artifact:", lpath + ".pdmodel")

config = inference.Config(lpath)
config.precision("bfloat16")
serve = inference.create_predictor(config)
prompt = np.random.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
serve.get_input_handle("input_ids").copy_from_cpu(prompt)
import jax as _jax

keys = np.stack([_jax.random.key_data(_jax.random.PRNGKey(i))
                 for i in range(NEW)])
serve.get_input_handle("rng_keys").copy_from_cpu(keys)
(ids_out,) = serve.run()
print("served generation:", np.asarray(ids_out))
assert np.asarray(ids_out).shape == (B, S + NEW)
print(f"OK: Predictor generated {NEW} tokens per row via the exported "
      "decode loop")
