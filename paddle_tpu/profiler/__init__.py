"""paddle_tpu.profiler — tracing and profiling.

Analog of /root/reference/python/paddle/profiler/ (Profiler:358 with
scheduler states, export_chrome_tracing, RecordEvent spans; C++ CUPTI
tracers in paddle/fluid/platform/profiler/). TPU-natively device timelines
come from the XLA/XPlane profiler (``jax.profiler``) — the CUPTI
equivalent — and host-side phases from RecordEvent spans recorded here and
via ``jax.profiler.TraceAnnotation``.
"""
from __future__ import annotations

import contextlib
import json
import os
import time

from ..core import telemetry

__all__ = [
    "Profiler", "ProfilerResult", "RecordEvent", "ProfilerTarget",
    "ProfilerState", "annotate", "make_scheduler",
    "export_chrome_tracing", "load_profiler_result",
]


@contextlib.contextmanager
def annotate(name, **span_args):
    """Hot-loop trace scope: a ``jax.profiler.TraceAnnotation`` (so the
    span shows up in a TPU XPlane trace around the host work it
    brackets) that ALSO records into the telemetry span sink — the same
    sink request tracing writes to — so ``export_chrome_tracing`` shows
    engine phases and per-request spans on one timeline. The serving
    engine wraps its prefill / chunked-prefill / segment dispatches and
    host bookkeeping in these (passing the dispatch's rids/trace ids as
    ``span_args``), which is how a pipelined schedule's host/device
    overlap is read off a trace. The sink write is skipped when
    ``FLAGS_telemetry`` is off; the XLA annotation always applies."""
    try:
        import jax.profiler as jp

        ctx = jp.TraceAnnotation(name)
    except Exception:
        ctx = contextlib.nullcontext()
    sink = telemetry.maybe_span(name, **span_args)
    with ctx, sink:
        yield sink


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "custom_device"
    TPU = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


_host_events: list = []
_active = False


class RecordEvent:
    """Host-side span (reference python/paddle/profiler/utils.py
    RecordEvent; C++ paddle/fluid/platform/profiler/host_tracer.cc).
    Annotates the XLA trace AND feeds the telemetry span sink — the one
    sink ``export_chrome_tracing`` exports, shared with request tracing
    and ``annotate`` scopes."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._t0_wall = None
        self._ann = None

    def begin(self):
        self._t0 = time.perf_counter_ns()
        self._t0_wall = time.time()  # wall-clock: x-process trace epoch
        try:
            import jax.profiler as jp

            self._ann = jp.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        if self._t0 is None:
            return
        dur_s = (time.perf_counter_ns() - self._t0) / 1e9
        if telemetry.enabled():
            telemetry.tracer().add_span(self.name, self._t0_wall, dur_s)
        elif _active:
            # telemetry off but a Profiler is recording: keep the legacy
            # host-event ring so export still sees the span (exactly one
            # of the two sinks records — export merges both)
            _host_events.append({
                "name": self.name, "ph": "X", "pid": os.getpid(), "tid": 0,
                "ts": self._t0_wall * 1e6, "dur": dur_s * 1e6,  # wall-clock: x-process trace epoch
            })

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Step-state scheduler (reference profiler.py make_scheduler)."""
    period = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = (step - skip_first) % max(period, 1)
        if repeat and (step - skip_first) // max(period, 1) >= repeat:
            return ProfilerState.CLOSED
        if s < closed:
            return ProfilerState.CLOSED
        if s < closed + ready:
            return ProfilerState.READY
        if s == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


class Profiler:
    """Reference python/paddle/profiler/profiler.py:358. ``start``/``stop``
    wrap ``jax.profiler.start_trace``/``stop_trace`` (XPlane → TensorBoard/
    Perfetto) plus the host-event ring for chrome export."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, profile_memory=False, with_flops=False):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._log_dir = None
        self._step = 0
        self._tracing = False
        self._step_times = []
        self._last_step_t = None
        self._t_start_wall = None

    def start(self):
        global _active
        _active = True
        _host_events.clear()
        # session window anchor: export() filters the (process-lifetime)
        # telemetry sink to spans recorded after this point, so a
        # profile of one step is not dominated by pre-session serving
        # spans already in the ring
        self._t_start_wall = time.time()  # wall-clock: x-process trace epoch
        self._last_step_t = time.perf_counter()
        if not self.timer_only:
            try:
                import jax.profiler as jp

                self._log_dir = os.environ.get(
                    "PADDLE_PROFILER_LOGDIR", "/tmp/paddle_tpu_profile")
                jp.start_trace(self._log_dir)
                self._tracing = True
            except Exception:
                self._tracing = False
        return self

    def stop(self):
        global _active
        _active = False
        if self._tracing:
            import jax.profiler as jp

            jp.stop_trace()
            self._tracing = False
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np

        arr = np.asarray(self._step_times)
        return (f"avg step {arr.mean()*1e3:.2f}ms "
                f"(min {arr.min()*1e3:.2f}, max {arr.max()*1e3:.2f}, "
                f"n={len(arr)})")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        print(self.step_info())
        print(f"host events recorded: {len(_host_events)}")

    def export(self, path, format="json"):
        # scoped to THIS profiler session (start() → now); the
        # module-level export_chrome_tracing dumps the whole sink
        return export_chrome_tracing(
            path, since_wall_s=getattr(self, "_t_start_wall", None))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def export_chrome_tracing(path, dir_name=None, since_wall_s=None):
    """Dump the telemetry span sink (request-trace spans, ``annotate``
    scopes, RecordEvent spans) plus any legacy host events as ONE
    chrome://tracing JSON (reference chrometracing_logger.cc analog; the
    device timeline lives in the XPlane dump under the jax.profiler log
    dir). ``since_wall_s`` restricts sink events to those recorded at
    or after that wall-clock time (``Profiler.export`` passes its
    session start, so one profiled step is not dominated by pre-session
    serving spans). The file round-trips through
    :func:`load_profiler_result`."""
    evs = telemetry.tracer().spans()
    if since_wall_s is not None:
        cut = since_wall_s * 1e6
        evs = [e for e in evs if e.get("ts", 0) >= cut]
    with open(path, "w") as f:
        json.dump({"traceEvents": evs + list(_host_events),
                   "displayTimeUnit": "ms"}, f)
    return path


class ProfilerResult(dict):
    """A loaded trace: a plain dict (``result["traceEvents"]`` — the
    historical surface) plus span accessors, so exported profiles
    round-trip as REAL span data, not an opaque blob."""

    @property
    def events(self) -> list:
        return self.get("traceEvents", [])

    def spans(self, name=None, trace=None) -> list:
        """Complete (``ph == "X"``) spans, optionally filtered by name
        and/or by the trace id carried in ``args`` (including batched
        spans whose ``args['traces']`` list contains it)."""
        out = [e for e in self.events if e.get("ph") == "X"]
        if name is not None:
            out = [e for e in out if e.get("name") == name]
        if trace is not None:
            out = [e for e in out
                   if e.get("args", {}).get("trace") == trace
                   or trace in (e.get("args", {}).get("traces") or ())]
        return out

    def span_names(self) -> set:
        return {e.get("name") for e in self.events}

    def total_dur_us(self, name) -> float:
        return sum(e.get("dur", 0.0) for e in self.spans(name))

    def save(self, path) -> str:
        with open(path, "w") as f:
            json.dump(dict(self), f)
        return path


def load_profiler_result(path) -> ProfilerResult:
    with open(path) as f:
        return ProfilerResult(json.load(f))
