"""GPT + BERT model families and incubate fused layers.

Mirrors the reference's GPT/BERT harnesses (BASELINE configs 2/3/5).
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.nn as nn
from paddle_tpu.models import (
    BertForPretraining,
    BertForSequenceClassification,
    BertPretrainingCriterion,
    GPTForCausalLM,
    GPTPretrainingCriterion,
    bert_tiny_config,
    gpt_shard_fn,
    gpt_tiny_config,
)


def test_gpt_trains():
    paddle.seed(0)
    model = GPTForCausalLM(gpt_tiny_config())
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    ids = paddle.to_tensor(np.tile(np.arange(16), (4, 1)))
    losses = []
    for _ in range(6):
        loss = crit(model(ids), ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9


def test_gpt_tp_sharding():
    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), ["dp", "mp"])
    model = GPTForCausalLM(gpt_tiny_config())
    dist.shard_layer(model, mesh, gpt_shard_fn(mesh))
    named = dict(model.named_parameters())
    qkv = named["gpt.h.0.attn.qkv_proj.weight"]
    assert qkv._value.addressable_shards[0].data.shape == (64, 96)
    ids = paddle.to_tensor(np.random.randint(0, 256, (4, 16)))
    assert model(ids).shape == [4, 16, 256]
    dist.process_mesh._global_mesh = None


def test_bert_pretraining_loss_decreases():
    paddle.seed(0)
    model = BertForPretraining(bert_tiny_config())
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    ids = paddle.to_tensor(np.tile(np.arange(16), (4, 1)))
    nsp = paddle.to_tensor(np.array([[0], [1], [0], [1]]))
    losses = []
    for _ in range(5):
        mlm_logits, nsp_logits = model(ids)
        loss = crit(mlm_logits, nsp_logits, ids, nsp)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_classifier_with_mask():
    model = BertForSequenceClassification(bert_tiny_config(), num_classes=3)
    ids = paddle.to_tensor(np.random.randint(0, 256, (2, 16)))
    mask = paddle.to_tensor(np.ones((2, 16), np.int64))
    tok = paddle.to_tensor(np.zeros((2, 16), np.int64))
    logits = model(ids, token_type_ids=tok, attention_mask=mask)
    assert logits.shape == [2, 3]


def test_fused_layers_standalone():
    from paddle_tpu.incubate.nn import (
        FusedFeedForward,
        FusedMultiHeadAttention,
        FusedTransformerEncoderLayer,
    )

    x = paddle.to_tensor(np.random.rand(2, 8, 32).astype(np.float32))
    attn = FusedMultiHeadAttention(32, 4, dropout_rate=0.0,
                                   attn_dropout_rate=0.0)
    assert attn(x).shape == [2, 8, 32]
    ffn = FusedFeedForward(32, 64, dropout_rate=0.0)
    assert ffn(x).shape == [2, 8, 32]
    enc = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
    y = enc(x)
    assert y.shape == [2, 8, 32]
    y.sum().backward()
    # pre_ln is constructed but unused in post-LN mode (reference keeps both
    # param sets too) - unused params legitimately have no grad
    for name, p in enc.named_parameters():
        if "pre_ln" in name:
            continue
        assert p.grad is not None, name
