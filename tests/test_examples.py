"""The examples/ scripts run end-to-end (CPU mode)."""
import subprocess
import sys

import pytest


@pytest.mark.parametrize("script", [
    "examples/train_llama_distributed.py",
    "examples/export_and_serve.py",
    "examples/train_ctr_ps.py",
    "examples/generate_llama.py",
])
def test_example_runs(script):
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, script, "--cpu"],
                          capture_output=True, text=True, timeout=300,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-1500:]
    assert ("done" in proc.stdout or "served output" in proc.stdout
            or "rows materialized" in proc.stdout)
