#!/usr/bin/env python
"""Repo-root entry for the chaos traffic generator.

Loads ``paddle_tpu/tools/trafficgen.py`` by FILE PATH (not package
import) so the schedule summary runs without importing the framework —
numpy only, no jax, no device contact (same trick as
``tools/bench_trend.py``).

    python tools/trafficgen.py --duration 30 --flash-at 10 --flash-mult 8
"""
import importlib.util
import os
import sys

_IMPL = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "tools", "trafficgen.py")


def _load():
    spec = importlib.util.spec_from_file_location("_trafficgen", _IMPL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load().main())
