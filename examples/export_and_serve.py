"""Export a trained model to a StableHLO artifact and serve it.

Run: python examples/export_and_serve.py [--cpu]
"""
import sys
import tempfile

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference
from paddle_tpu.static import InputSpec

paddle.seed(0)
model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 4))
path = tempfile.mkdtemp() + "/model"
paddle.jit.save(model, path, input_spec=[InputSpec([1, 16], "float32")])
print("exported StableHLO artifact:", path + ".pdmodel")

predictor = inference.create_predictor(inference.Config(path))
x = np.random.rand(1, 16).astype(np.float32)
predictor.get_input_handle(predictor.get_input_names()[0]).copy_from_cpu(x)
(out,) = predictor.run()
print("served output:", out)
