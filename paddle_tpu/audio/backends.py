"""paddle.audio.backends — WAV IO (reference wave_backend).

Analog of /root/reference/python/paddle/audio/backends/wave_backend.py:
PCM WAV load/save/info over the stdlib ``wave`` module (the reference's
default backend when paddleaudio is absent). Only the wave backend exists
in this build; ``set_backend`` accepts it for API parity."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save",
           "list_available_backends", "get_current_backend", "set_backend"]


class AudioInfo:
    """Reference backends.backend.AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"audio backend {backend_name!r} unavailable; this build ships "
            f"{list_available_backends()}")


def info(filepath):
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8,
                         f"PCM_{'S' if f.getsampwidth() > 1 else 'U'}")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (waveform Tensor, sample_rate). ``normalize`` scales PCM to
    [-1, 1] float32 (reference wave_backend.load semantics)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        width = f.getsampwidth()
        channels = f.getnchannels()
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(max(n, 0))
    dtype = {1: np.uint8, 2: np.int16, 4: np.int32}.get(width)
    if dtype is None:
        raise ValueError(f"unsupported PCM sample width {width}")
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, channels)
    if normalize:
        if width == 1:
            data = (data.astype(np.float32) - 128.0) / 128.0
        else:
            data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    wav = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(wav)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Write float waveform in [-1, 1] (or int16) as PCM16 WAV."""
    data = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T
    if data.ndim == 1:
        data = data[:, None]
    if np.issubdtype(data.dtype, np.floating):
        data = np.clip(data, -1.0, 1.0)
        data = (data * (2 ** 15 - 1)).astype(np.int16)
    else:
        data = data.astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(data).tobytes())
