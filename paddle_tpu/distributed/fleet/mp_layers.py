"""Tensor-parallel layers: VocabParallelEmbedding, ColumnParallelLinear,
RowParallelLinear, ParallelCrossEntropy.

Analog of /root/reference/python/paddle/distributed/fleet/layers/mpu/
mp_layers.py (VocabParallelEmbedding:49, ColumnParallelLinear:336,
RowParallelLinear:543, ParallelCrossEntropy:744) and mp_ops.py. The
reference implements Megatron TP by hand: slice weights per rank, insert
_c_identity/_mp_allreduce collectives with custom grads. TPU-natively the
layers declare *shardings* (weight sharded over the ``mp`` mesh axis,
activations constrained at region boundaries) and GSPMD derives exactly
those collectives — including the backward all-reduces — at compile time.
The hand-rolled f/g pair still exists for shard_map code in
distributed/comm_ops.py.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ..api import shard_constraint, shard_tensor
from ..placement import Replicate, Shard
from ..process_mesh import ProcessMesh, get_mesh

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy",
]


def _resolve_mesh(mesh, mp_axis):
    mesh = mesh or get_mesh()
    if mesh is None:
        return None, None
    if mp_axis not in mesh.dim_names:
        return mesh, None
    return mesh, mesh.dim_names.index(mp_axis)


def _shard_param(p, mesh, mp_index, tensor_dim):
    pl = [Replicate()] * mesh.ndim
    if mp_index is not None:
        pl[mp_index] = Shard(tensor_dim)
    shard_tensor(p, mesh, pl)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:49; kernel
    c_embedding_kernel.cu). Out-of-shard ids hit zero rows in the reference;
    under GSPMD the gather is partitioned automatically and the result is
    correct without masking."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, mesh: ProcessMesh | None = None,
                 mp_axis="mp", name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        mesh, mp = _resolve_mesh(mesh, mp_axis)
        if mesh is not None:
            _shard_param(self.weight, mesh, mp, 0)
        self._mesh = mesh

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """Linear with output features sharded over mp (mp_layers.py:336).
    ``gather_output=False`` leaves the activation sharded for a following
    RowParallelLinear (the Megatron pairing)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, mesh: ProcessMesh | None = None,
                 mp_axis="mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierNormal(),
        )
        has_bias = True if has_bias is None else has_bias
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        mesh, mp = _resolve_mesh(mesh, mp_axis)
        if mesh is not None:
            _shard_param(self.weight, mesh, mp, 1)
            if self.bias is not None:
                _shard_param(self.bias, mesh, mp, 0)
        self._mesh, self._mp = mesh, mp
        self._mp_axis = mp_axis

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self._mesh is not None and self._mp is not None:
            pl = [Replicate()] * self._mesh.ndim
            if not self.gather_output:
                pl[self._mp] = Shard(y.ndim - 1)  # keep column-sharded
            y = shard_constraint(y, self._mesh, pl)
        return y


class RowParallelLinear(Layer):
    """Linear with input features sharded over mp (mp_layers.py:543): takes
    the column-sharded activation from ColumnParallelLinear; the product is
    Partial over mp and GSPMD inserts the closing all-reduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, mesh: ProcessMesh | None = None,
                 mp_axis="mp", name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features),
            attr=weight_attr, default_initializer=I.XavierNormal(),
        )
        # bias replicated: added after the mp reduction (reference semantics)
        self.bias = self.create_parameter(
            (out_features,), is_bias=True) if has_bias else None
        mesh, mp = _resolve_mesh(mesh, mp_axis)
        if mesh is not None:
            _shard_param(self.weight, mesh, mp, 0)
            if self.bias is not None:
                _shard_param(self.bias, mesh, None, 0)
        self._mesh, self._mp = mesh, mp

    def forward(self, x):
        y = F.linear(x, self.weight, None)
        if self._mesh is not None and self._mp is not None:
            # result of (col-sharded x) @ (row-sharded w) is Partial(mp):
            # constrain replicated → all-reduce over mp at compile time
            y = shard_constraint(
                y, self._mesh, [Replicate()] * self._mesh.ndim)
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy (mp_layers.py:744, kernel
    c_softmax_with_cross_entropy): local max/sum-exp/masked-pick with the
    cross-shard all-reduces derived by GSPMD from the logits' sharding —
    the full logits row is never gathered onto one shard (HLO-audited in
    tests/test_fleet.py)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        from ...ops import c_softmax_with_cross_entropy

        return c_softmax_with_cross_entropy(
            input, label, ignore_index=self.ignore_index)
