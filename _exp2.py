import time, sys, functools
import jax, jax.numpy as jnp, numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM, LlamaPretrainingCriterion
from paddle_tpu.jit import _FunctionalModel

def sync(x): return float(jnp.asarray(x).sum())

def measure(h, L, inter, heads, batch, seq, steps=6):
    cfg = LlamaConfig(vocab_size=32000, hidden_size=h, intermediate_size=inter,
                      num_hidden_layers=L, num_attention_heads=heads,
                      max_position_embeddings=seq)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg); model.to(dtype="bfloat16")
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    crit = LlamaPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(), multi_precision=True)
    functional = _FunctionalModel(model)
    params, buffers = model.raw_state()
    opt.register_param_names(dict(model.named_parameters()))
    accs, masters = opt.init_functional_state(params)
    ids = jnp.asarray(np.random.randint(0, 32000, (batch, seq)).astype(np.int32))
    rng = jax.random.key_data(jax.random.PRNGKey(0))
    def loss_of(p):
        out, _ = functional(p, buffers, (paddle.Tensor._from_value(ids),), {}, rng)
        ov = out._value if hasattr(out, '_value') else out
        return crit(paddle.Tensor._from_value(ov), paddle.Tensor._from_value(ids))._value
    def one(carry, _):
        p,a,m,t = carry
        loss, grads = jax.value_and_grad(loss_of)(p)
        p2,a2,m2 = opt.functional_update(p, grads, a, m, jnp.asarray(1e-4, jnp.float32), t)
        return (p2,a2,m2,t+1), loss
    @functools.partial(jax.jit, donate_argnums=(0,1,2))
    def run(p,a,m):
        (p,a,m,_), losses = jax.lax.scan(one, (p,a,m,jnp.asarray(1,jnp.int32)), None, length=steps)
        return p,a,m,losses
    try:
        params, accs, masters, losses = run(params, accs, masters)
        sync(losses)
        t0=time.time()
        params, accs, masters, losses = run(params, accs, masters)
        sync(losses)
        dt=(time.time()-t0-0.05)/steps
        tps = batch*seq/dt
        fpt = 6*n_params + 12*L*h*seq
        mfu = tps*fpt/240e12
        print(f"h={h} L={L} b={batch} s={seq} ({n_params/1e6:.0f}M): {dt*1e3:.1f}ms {tps:,.0f} tok/s MFU~{mfu*100:.1f}%", flush=True)
    except Exception as e:
        print(f"h={h} L={L} b={batch} s={seq}: FAILED {str(e)[:120]}", flush=True)

measure(2048, 12, 5504, 16, 2, 1536)
measure(2048, 10, 5504, 16, 4, 1536)
measure(1536, 12, 4096, 12, 6, 1536)
