"""Hardened RPC transport (reference paddle.distributed.rpc) — ISSUE 7.

The robustness contract under the cross-process serving fleet:
at-least-once delivery with ack-after-execute, rid-idempotent dedup on
the callee (a resent request never re-executes), bounded store growth
(reply + inbox slot keys are GC'd), a worker pool so a slow call cannot
head-of-line-block a health probe, typed remote errors, and
retry-budgeted resends that drill through the deterministic fault sites
``rpc.send_drop`` / ``rpc.reply_drop`` / ``rpc.delay``.
"""
import operator
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience
from paddle_tpu.core.flags import set_flags
from paddle_tpu.core.resilience import (
    CommTimeoutError,
    RetryPolicy,
    ServingUnavailable,
)
from paddle_tpu.distributed import rpc


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.reset_faults()
    resilience.reset_counters()
    yield
    resilience.reset_faults()
    resilience.reset_counters()


@pytest.fixture
def rpc_env():
    store = rpc.init_rpc("worker0", rank=0, world_size=1)
    yield store
    rpc.shutdown()


# ------------------------------------------------------------- basics


def test_rpc_sync_scalar(rpc_env):
    assert rpc.rpc_sync("worker0", operator.add, args=(3, 4)) == 7


def test_rpc_tensor_payload(rpc_env):
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = rpc.rpc_sync("worker0", np.sum, args=(x,))
    assert out == 15.0
    y = rpc.rpc_sync("worker0", np.transpose, args=(x,))
    np.testing.assert_array_equal(y, x.T)


def test_rpc_async_futures(rpc_env):
    futs = [rpc.rpc_async("worker0", operator.mul, args=(i, i))
            for i in range(5)]
    assert [f.wait() for f in futs] == [0, 1, 4, 9, 16]


def test_worker_info(rpc_env):
    info = rpc.get_worker_info()
    assert info.name == "worker0" and info.rank == 0
    assert rpc.get_worker_info("worker0").rank == 0


def test_worker_info_unknown_name_honors_timeout(rpc_env):
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="ghost"):
        rpc.get_worker_info("ghost", timeout=0.2)
    # must not fall into the store's 900s rendezvous default
    assert time.monotonic() - t0 < 5.0


# ----------------------------------------------------- in-memory codec


def test_codec_round_trips_nested_payloads():
    from paddle_tpu.distributed.rpc import _decode, _encode

    x = np.arange(12, dtype=np.int32).reshape(3, 4)
    payload = {
        "rows": [[7, "ok", x, None], (1, 2.5)],
        17: {"nested": x.astype(np.float64)},   # non-string dict key
        "empty": np.zeros((0,), np.int32),
    }
    out = _decode(_encode(payload))
    assert out["rows"][1] == (1, 2.5)           # tuples survive
    np.testing.assert_array_equal(out["rows"][0][2], x)
    np.testing.assert_array_equal(out[17]["nested"], x.astype(np.float64))
    assert out[17]["nested"].dtype == np.float64
    assert out["empty"].size == 0
    assert out["rows"][0][3] is None


def test_codec_no_tempfile_and_no_dead_io_import():
    import pathlib

    src = pathlib.Path(rpc.__file__).read_text()
    assert "import tempfile" not in src  # in-memory encode only
    assert "_pyio" not in src            # the dead io alias is gone


# ----------------------------------------------------- typed remote errors


def test_remote_builtin_error_reraises_typed(rpc_env):
    with pytest.raises(ZeroDivisionError, match="division by zero") as ei:
        rpc.rpc_sync("worker0", operator.truediv, args=(1, 0))
    assert ei.value.remote_traceback  # remote frames ride along


def _raise_serving_unavailable():
    raise ServingUnavailable("replica gone (drill)")


def test_remote_resilience_error_reraises_typed(rpc_env):
    with pytest.raises(ServingUnavailable, match="replica gone"):
        rpc.rpc_sync("worker0", _raise_serving_unavailable)


class _ExoticError(Exception):
    pass


def _raise_exotic():
    raise _ExoticError("no such type caller-side")


def test_remote_unknown_error_wraps_as_rpc_remote_error(rpc_env):
    with pytest.raises(rpc.RpcRemoteError,
                       match="_ExoticError: no such type"):
        rpc.rpc_sync("worker0", _raise_exotic)


def _return_unserializable():
    return {1, 2, 3}  # a set does not survive the codec


def test_unserializable_result_errors_instead_of_hanging(rpc_env):
    """A result the codec cannot encode must come back as a typed error
    reply, not strand the caller until its overall timeout with the
    request poisoned at 'pending' and its inbox slot never acked."""
    t0 = time.monotonic()
    with pytest.raises(TypeError, match="not JSON serializable"):
        rpc.rpc_sync("worker0", _return_unserializable, timeout=30.0)
    assert time.monotonic() - t0 < 10.0  # the error reply, not the timeout
    # the slot was acked and the dispatcher still serves
    assert rpc.rpc_sync("worker0", operator.add, args=(2, 2),
                        timeout=10.0) == 4


# -------------------------------------------------- bounded store growth


def test_reply_and_inbox_keys_are_gcd(rpc_env):
    """Across N calls the per-call store keys (reply + inbox slot) must
    all be gone — only the two per-worker inbox counters persist."""
    store = rpc_env
    n = 12
    futs = [rpc.rpc_async("worker0", operator.add, args=(i, 1))
            for i in range(n)]
    ids = [f._id for f in futs]
    assert [f.wait(timeout=30) for f in futs] == list(range(1, n + 1))
    for req_id in ids:
        assert not store.check(f"rpc/reply/{req_id}")
    deadline = time.monotonic() + 10
    while (any(store.check(f"rpc/inbox/worker0/{s}") for s in range(n))
           and time.monotonic() < deadline):
        time.sleep(0.01)  # the post-execute ack is asynchronous
    for slot in range(n):
        assert not store.check(f"rpc/inbox/worker0/{slot}")
    assert int(store.add("rpc/inbox/worker0", 0)) == n
    assert int(store.add("rpc/inbox/worker0/claimed", 0)) == n


# -------------------------------------------------------- worker pool

_slow_gate = threading.Event()


def _slow_call():
    _slow_gate.wait(10.0)
    return "slow done"


def test_slow_call_does_not_block_concurrent_probe(rpc_env):
    """Head-of-line blocking drill: while one pool worker is stuck in a
    slow call, a health-probe-shaped fast call must still answer."""
    _slow_gate.clear()
    try:
        slow = rpc.rpc_async("worker0", _slow_call)
        t0 = time.monotonic()
        assert rpc.rpc_sync("worker0", operator.add, args=(1, 1),
                            timeout=5.0) == 2
        assert time.monotonic() - t0 < 5.0
        assert not slow.done()
    finally:
        _slow_gate.set()
    assert slow.wait(timeout=10) == "slow done"


def test_delay_fault_stalls_one_call_not_the_pool(rpc_env):
    set_flags({"FLAGS_fault_injection": "rpc.delay:1"})
    delayed = rpc.rpc_async("worker0", operator.add, args=(1, 2))
    time.sleep(0.02)  # let the delayed call claim its pool worker
    t0 = time.monotonic()
    assert rpc.rpc_sync("worker0", operator.add, args=(3, 4),
                        timeout=5.0) == 7
    overtake = time.monotonic() - t0
    assert delayed.wait(timeout=10) == 3
    assert overtake < rpc.DELAY_FAULT_S
    assert resilience.get_counter("rpc.delayed") == 1


# ---------------------------------------- retries, dedup, fault drills

_effects_lock = threading.Lock()
_effects: list = []


def _record_effect(tag):
    with _effects_lock:
        _effects.append(tag)
    return len(_effects)


def test_send_drop_recovered_by_resend_exactly_once(rpc_env):
    """The send vanishes on the wire: the resend budget re-posts it and
    the observable effect happens exactly once."""
    del _effects[:]
    set_flags({"FLAGS_fault_injection": "rpc.send_drop:1"})
    out = rpc.rpc_sync("worker0", _record_effect, args=("a",),
                       timeout=30.0, retry=3, resend_after=0.2)
    assert out == 1
    assert _effects == ["a"]
    assert resilience.get_counter("rpc.send_dropped") == 1
    assert resilience.get_counter("rpc.resend") >= 1


def test_reply_drop_resend_dedups_no_reexecution(rpc_env):
    """The reply vanishes AFTER the callee executed: the resend must hit
    the rid dedup cache — the cached reply is re-written, the side
    effect happens exactly once (exactly-once observable effects)."""
    del _effects[:]
    set_flags({"FLAGS_fault_injection": "rpc.reply_drop:1"})
    out = rpc.rpc_sync("worker0", _record_effect, args=("b",),
                       timeout=30.0, retry=4, resend_after=0.2)
    assert out == 1
    assert _effects == ["b"]
    assert resilience.get_counter("rpc.reply_dropped") == 1
    assert resilience.get_counter("rpc.redelivered") >= 1


def test_retry_accepts_retry_policy_budget(rpc_env):
    del _effects[:]
    set_flags({"FLAGS_fault_injection": "rpc.send_drop:1"})
    out = rpc.rpc_sync(
        "worker0", _record_effect, args=("c",), timeout=30.0,
        retry=RetryPolicy(max_attempts=3, base_delay=0.01),
        resend_after=0.2)
    assert out == 1 and _effects == ["c"]


def test_exhausted_retry_budget_names_the_peer(rpc_env):
    """Every send drops: the budget burns down and surfaces a
    CommTimeoutError naming src/dst and the request."""
    set_flags({"FLAGS_fault_injection": "rpc.send_drop:*"})
    with pytest.raises(CommTimeoutError) as ei:
        rpc.rpc_sync("worker0", operator.add, args=(1, 1),
                     timeout=1.5, retry=3, resend_after=0.2)
    msg = str(ei.value)
    assert "worker0" in msg
    assert ei.value.dst == "worker0" and ei.value.src == "worker0"
    assert resilience.get_counter("rpc.send_dropped") >= 3


def test_no_reply_without_retry_times_out_naming_peer(rpc_env):
    set_flags({"FLAGS_fault_injection": "rpc.send_drop:*"})
    with pytest.raises(CommTimeoutError, match="worker0"):
        rpc.rpc_sync("worker0", operator.add, args=(1, 1), timeout=0.5)


def test_resend_after_without_retry_tolerates_slow_execution(rpc_env):
    """resend_after with NO retry budget must not convert a slow
    execution into 'exhausted retry budget': one attempt means no
    resends ever happen (so no claimed receipt can exist to save the
    call) — only the overall timeout bounds it."""
    _slow_gate.clear()
    try:
        fut = rpc.rpc_async("worker0", _slow_call, timeout=30.0,
                            resend_after=0.1)
        threading.Timer(1.0, _slow_gate.set).start()
        assert fut.wait() == "slow done"  # NOT CommTimeoutError at ~0.35s
    finally:
        _slow_gate.set()


def test_retry_without_timeout_still_resends_and_raises(rpc_env):
    """retry= with neither timeout nor resend_after must still re-post
    (default cadence) and exhaust — not silently disable the budget and
    hang forever on a lost send."""
    set_flags({"FLAGS_fault_injection": "rpc.send_drop:*"})
    t0 = time.monotonic()
    with pytest.raises(CommTimeoutError, match="retry budget"):
        rpc.rpc_sync("worker0", operator.add, args=(1, 1), retry=2)
    assert time.monotonic() - t0 < rpc.DEFAULT_RESEND_AFTER_S * 2 + 5.0
    assert resilience.get_counter("rpc.resend") >= 1


def test_timeout_gcs_claimed_and_reply_keys(rpc_env):
    """A caller that gives up must not leave its claimed receipt (or a
    reply that landed after it stopped checking) in the store forever."""
    store = rpc_env
    _slow_gate.clear()
    try:
        fut = rpc.rpc_async("worker0", _slow_call, timeout=0.8,
                            retry=3, resend_after=0.1)
        with pytest.raises(CommTimeoutError):
            fut.wait()
        # the resends were dropped as in-flight duplicates, so the
        # claimed marker exists right up until the abandon-GC removes it
        assert resilience.get_counter("rpc.claimed_wait") >= 1
        assert not store.check(f"rpc/claimed/{fut._id}")
        assert not store.check(f"rpc/reply/{fut._id}")
    finally:
        _slow_gate.set()


def test_evicted_unconsumed_replies_are_gcd():
    """An abandoned caller's reply key is deleted callee-side when its
    id falls out of the dedup window — store growth stays bounded even
    when the caller never consumes."""
    store = rpc.init_rpc("evict", rank=0, world_size=1, dedup_window=4)
    try:
        fut = rpc.rpc_async("evict", operator.add, args=(1, 1),
                            timeout=10.0)
        deadline = time.monotonic() + 10
        while (not store.check(f"rpc/reply/{fut._id}")
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert store.check(f"rpc/reply/{fut._id}")
        for i in range(8):  # roll the abandoned id out of the window
            rpc.rpc_sync("evict", operator.add, args=(i, 1), timeout=10.0)
        deadline = time.monotonic() + 10
        while (store.check(f"rpc/reply/{fut._id}")
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert not store.check(f"rpc/reply/{fut._id}")
    finally:
        rpc.shutdown()


def test_shutdown_restores_switch_interval():
    import sys

    prev = sys.getswitchinterval()
    rpc.init_rpc("swint", rank=0, world_size=1)
    try:
        assert sys.getswitchinterval() == 0.0005
    finally:
        rpc.shutdown()
    assert sys.getswitchinterval() == prev


def test_duplicate_post_executes_once(rpc_env):
    """Transport-level rid idempotency: the same encoded request posted
    twice (a duplicated message on the wire) executes once; the second
    delivery hits the dedup cache — its cached reply is re-written, the
    side effect is NOT repeated."""
    from paddle_tpu.distributed.rpc import _encode, _fn_ref, _post

    del _effects[:]
    store = rpc_env
    state = rpc._state
    fut = rpc.rpc_async("worker0", _record_effect, args=("dup",),
                        timeout=30.0)
    assert fut.wait() == 1
    assert not store.check(f"rpc/reply/{fut._id}")  # consumed + GC'd
    # duplicate the message on the wire: re-post the SAME request blob
    req = {"id": fut._id, "fn": _fn_ref(_record_effect),
           "args": ("dup",), "kwargs": {}}
    _post(state, "worker0", _encode(req))
    deadline = time.monotonic() + 10
    while (not store.check(f"rpc/reply/{fut._id}")
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert store.check(f"rpc/reply/{fut._id}")  # cached reply re-written
    assert _effects == ["dup"]                  # NOT re-executed
    assert resilience.get_counter("rpc.redelivered") == 1
    store.delete_key(f"rpc/reply/{fut._id}")


def test_dedup_window_is_bounded():
    rpc.init_rpc("bounded", rank=0, world_size=1, dedup_window=8)
    try:
        state = rpc._state
        for i in range(30):
            rpc.rpc_sync("bounded", operator.add, args=(i, 1))
        assert len(state.seen) <= 8
    finally:
        rpc.shutdown()


# -------------------------------------- crash recovery (ack-after-execute)


def test_unacked_slot_is_reserved_after_restart():
    """Ack-after-execute: a slot a dead dispatcher claimed but never
    acked survives in the store; the next incarnation re-serves it
    (resume_inbox=True) and counts the replay."""
    from paddle_tpu.distributed.rpc import _encode
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)  # survives dispatcher restarts
    endpoint = f"127.0.0.1:{master.port}"
    try:
        # the store state a crashed dispatcher leaves behind: a request
        # enqueued exactly as _post would, claimed (counter bumped) but
        # never acked — the slot key is still there
        req = {"id": "deadbeef01", "fn": "operator:add", "args": (20, 22)}
        slot = int(master.add("rpc/inbox/crashy", 1)) - 1
        master.add("rpc/inbox/crashy/claimed", 1)
        master.set(f"rpc/inbox/crashy/{slot}", _encode(req))

        rpc.init_rpc("crashy", rank=1, master_endpoint=endpoint,
                     resume_inbox=True)
        try:
            deadline = time.monotonic() + 10
            while (not master.check("rpc/reply/deadbeef01")
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert master.check("rpc/reply/deadbeef01"), \
                "unacked slot not re-served"
            assert resilience.get_counter("rpc.redelivered") >= 1
        finally:
            rpc.shutdown()
    finally:
        master.close()


def test_recovery_serves_slot_enqueued_in_the_write_gap():
    """At-least-once across restart: a slot whose inbox counter bump
    landed but whose blob write hadn't yet (the enqueue/write gap) must
    be served once the blob lands — not silently skipped by recovery
    with the claimed counter advanced past it."""
    from paddle_tpu.distributed.rpc import _encode
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    endpoint = f"127.0.0.1:{master.port}"
    try:
        slot = int(master.add("rpc/inbox/gappy", 1)) - 1  # bump landed
        rpc.init_rpc("gappy", rank=1, master_endpoint=endpoint,
                     resume_inbox=True)
        try:
            time.sleep(0.1)  # recovery has scanned; blob lands late
            req = {"id": "gap01", "fn": "operator:add", "args": (2, 3)}
            master.set(f"rpc/inbox/gappy/{slot}", _encode(req))
            deadline = time.monotonic() + 10
            while (not master.check("rpc/reply/gap01")
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert master.check("rpc/reply/gap01"), "in-gap slot dropped"
        finally:
            rpc.shutdown()
    finally:
        master.close()


def test_purge_inbox_on_restart_for_serving_replicas():
    """resume_inbox=False (serving replicas): a fresh incarnation purges
    unacked slots instead of replaying a dead fleet epoch's traffic."""
    from paddle_tpu.distributed.rpc import _encode
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    endpoint = f"127.0.0.1:{master.port}"
    try:
        req = {"id": "cafebabe02", "fn": "operator:add", "args": (1, 2)}
        slot = int(master.add("rpc/inbox/fresh", 1)) - 1
        master.add("rpc/inbox/fresh/claimed", 1)
        master.set(f"rpc/inbox/fresh/{slot}", _encode(req))

        rpc.init_rpc("fresh", rank=1, master_endpoint=endpoint,
                     resume_inbox=False)
        try:
            deadline = time.monotonic() + 10
            while (master.check(f"rpc/inbox/fresh/{slot}")
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert not master.check(f"rpc/inbox/fresh/{slot}")
            assert resilience.get_counter("rpc.purged") == 1
            time.sleep(0.1)
            assert not master.check("rpc/reply/cafebabe02")  # not executed
        finally:
            rpc.shutdown()
    finally:
        master.close()
