"""vision.transforms — image preprocessing.

Analog of /root/reference/python/paddle/vision/transforms/ (transforms.py +
functional.py). Numpy host-side preprocessing (runs in DataLoader workers);
images are HWC uint8/float ndarrays in, CHW float32 Tensors out of
``ToTensor`` — matching the reference's conventions.
"""
from __future__ import annotations

import numbers

import numpy as np

__all__ = [
    "Compose", "ToTensor", "Normalize", "Resize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Pad", "Transpose",
    "BrightnessTransform", "ContrastTransform", "RandomResizedCrop",
    "to_tensor", "normalize", "resize", "center_crop", "hflip", "vflip", "pad",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        if h < w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    if (oh, ow) == (h, w):
        return img
    # bilinear via jax-free numpy sampling (nearest for 'nearest')
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    out = ((1 - wy) * (1 - wx) * f[y0[:, None], x0[None, :]]
           + (1 - wy) * wx * f[y0[:, None], x1[None, :]]
           + wy * (1 - wx) * f[y1[:, None], x0[None, :]]
           + wy * wx * f[y1[:, None], x1[None, :]])
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return img[i:i + th, j:j + tw]


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4
    if len(padding) == 2:
        padding = (padding[0], padding[1]) * 2
    pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)


def to_tensor(img, data_format="CHW"):
    from ..core.tensor import Tensor

    img = _as_hwc(img)
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    from ..core.tensor import Tensor

    arr = np.asarray(img._value if isinstance(img, Tensor) else img,
                     dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size, self.interpolation = size, interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.padding, self.pad_if_needed, self.fill = (
            size, padding, pad_if_needed, fill)

    def __call__(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, max(th - h, 0), 0, max(tw - w, 0)), self.fill)
            h, w = img.shape[:2]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return img[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.scale, self.ratio = size, scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return resize(img[i:i + th, j:j + tw], self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else _as_hwc(img)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def __call__(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        img = _as_hwc(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        out = img.astype(np.float32) * alpha
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        img = _as_hwc(img)
        alpha = 1 + np.random.uniform(-self.value, self.value)
        f = img.astype(np.float32)
        mean = f.mean()
        out = mean + alpha * (f - mean)
        return np.clip(out, 0, 255).astype(img.dtype) \
            if img.dtype == np.uint8 else out
