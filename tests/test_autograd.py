"""Autograd engine tests (reference analog: eager backward tests; covers
VERDICT round-1 weak items 3 and 8 and ADVICE high finding)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(x, sg=False):
    out = paddle.to_tensor(np.asarray(x, dtype="float32"))
    out.stop_gradient = sg
    return out


def test_multi_depth_leaf_reuse():
    # ADVICE high: loss = x + x*y must give dx = 1 + y
    x, y = t(2.0), t(3.0)
    loss = x + x * y
    loss.backward()
    assert float(x.grad) == pytest.approx(4.0)
    assert float(y.grad) == pytest.approx(2.0)


def test_diamond_dag():
    x = t(2.0)
    a = x * 3.0
    b = x * 5.0
    loss = (a * b).sum()
    loss.backward()
    # d/dx (15 x^2) = 30x = 60
    assert float(x.grad) == pytest.approx(60.0)


def test_grad_accumulates_across_backwards():
    x = t([1.0, 2.0])
    (x * 2).sum().backward()
    (x * 3).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])


def test_retain_graph():
    x = t(1.0)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert float(x.grad) == pytest.approx(4.0)


def test_second_backward_without_retain_raises():
    x = t(1.0)
    y = x * x
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_grad_api_leaf():
    x, y = t(2.0), t(3.0)
    z = x * y
    (gx,) = paddle.grad(z, x)
    assert float(gx) == pytest.approx(3.0)
    assert x.grad is None  # grad() must not touch .grad


def test_grad_api_non_leaf_intermediate():
    # VERDICT weak-3: grad w.r.t. an intermediate tensor
    x = t(2.0)
    h = x * x      # intermediate
    z = h * 3.0
    (gh,) = paddle.grad(z, h)
    assert float(gh) == pytest.approx(3.0)


def test_grad_allow_unused():
    x, y = t(1.0), t(1.0)
    z = x * 2
    gx, gy = paddle.grad(z, [x, y], allow_unused=True)
    assert float(gx) == pytest.approx(2.0)
    assert gy is None


def test_grad_unused_raises_without_flag():
    x, y = t(1.0), t(1.0)
    z = x * 2
    with pytest.raises(RuntimeError):
        paddle.grad(z, [y])


def test_no_grad_context():
    x = t(1.0)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_stop_gradient_blocks_flow():
    x, w = t(1.0), t(2.0)
    y = x.detach() * w
    y.backward()
    assert x.grad is None
    assert float(w.grad) == pytest.approx(1.0)


def test_split_multi_output_grads():
    x = t(np.arange(6.0).reshape(2, 3))
    a, b = paddle.split(x, 2, axis=0)
    (a.sum() * 2 + b.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [[2, 2, 2], [3, 3, 3]])


def test_concat_variadic_grads():
    a, b = t([1.0, 2.0]), t([3.0, 4.0])
    c = paddle.concat([a, b])
    (c * paddle.to_tensor(np.array([1.0, 2, 3, 4], "float32"))).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [1, 2])
    np.testing.assert_allclose(b.grad.numpy(), [3, 4])


def test_integer_output_no_grad():
    x = t([3.0, 1.0, 2.0])
    vals, idx = paddle.topk(x, 2)
    assert idx.stop_gradient
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 0, 1])


def test_register_hook_on_leaf():
    x = t(1.0)
    x.register_hook(lambda g: g * 10)
    (x * 2).backward()
    assert float(x.grad) == pytest.approx(20.0)


def test_backward_nonscalar_requires_grad_tensor():
    x = t([1.0, 2.0])
    y = x * 2
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2
    y2.backward(paddle.ones([2]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_softplus_large_x_grad_finite():
    # ADVICE medium: softplus gradient must not be NaN for x > 20
    x = t([25.0, 50.0])
    y = paddle.nn.functional.softplus(x)
    y.sum().backward()
    assert np.isfinite(x.grad.numpy()).all()
    np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0], rtol=1e-5)


def test_check_nan_inf_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = t([1.0])
        with pytest.raises(FloatingPointError):
            paddle.log(x - 2.0)  # log of negative -> nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_inplace_mutation_cannot_stale_gradients():
    """VERDICT r1 weak-9: the reference tracks inplace versions because its
    buffers alias; here jax arrays are immutable, so a backward rule's saved
    operand is a snapshot — in-place rebinding of Tensor._value after use in
    a graph cannot corrupt gradients."""
    import numpy as np

    import paddle_tpu as paddle

    z = paddle.to_tensor(np.full(3, 3.0, np.float32), stop_gradient=False)
    w = z * z  # backward needs z's value (saved snapshot)
    z.add_(paddle.to_tensor(np.full(3, 100.0, np.float32)))  # mutate after
    w.sum().backward()
    # grad = 2 * z_original = 6, NOT 2 * 103
    np.testing.assert_allclose(np.asarray(z.grad._value), 6.0 * np.ones(3))


def test_cached_backward_distinguishes_call_patterns():
    """Regression: pow(x_t, y_t) and x_t ** scalar share value structure but
    must compile distinct backward executables (cache-key collision made one
    pattern reuse the other's executable)."""
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
    # pattern A: tensor ** python scalar (exponent coerced to raw array)
    (x ** 2.0).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value), [4.0, 6.0],
                               rtol=1e-6)
    x.clear_grad()
    # pattern B: pow(tensor, tensor) — same shapes, same attrs
    y = paddle.to_tensor(np.array([3.0, 2.0], np.float32), stop_gradient=False)
    paddle.pow(x, y).sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._value),
                               [3 * 4.0, 2 * 3.0], rtol=1e-6)  # y*x^(y-1)
    np.testing.assert_allclose(np.asarray(y.grad._value),
                               [8 * np.log(2), 9 * np.log(3)], rtol=1e-5)


def test_cached_backward_rng_key_not_baked():
    """Regression: dropout's rng_key (a raw array input) must ride into the
    cached backward as an argument — a baked first-call key would make every
    later backward replay the first mask."""
    import numpy as np

    import paddle_tpu as paddle

    paddle.seed(123)
    x = paddle.to_tensor(np.ones((64,), np.float32), stop_gradient=False)
    masks = []
    for _ in range(3):
        y = paddle.dropout(x, p=0.5)
        y.sum().backward()
        # grad == mask/keep_prob: must match THIS call's forward mask
        fwd_mask = (np.asarray(y._value) != 0).astype(np.float32) / 0.5
        np.testing.assert_allclose(np.asarray(x.grad._value), fwd_mask,
                                   rtol=1e-6)
        masks.append(fwd_mask.tobytes())
        x.clear_grad()
    assert len(set(masks)) > 1  # different draws across calls


# ---------------------------------------------------------------- create_graph


def test_create_graph_third_order():
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.float32(2.0), stop_gradient=False)
    y = x * x * x
    (g,) = paddle.grad(y, x, create_graph=True)
    assert not g.stop_gradient
    np.testing.assert_allclose(float(g), 12.0)
    (g2,) = paddle.grad(g, x, create_graph=True)
    np.testing.assert_allclose(float(g2), 12.0)
    (g3,) = paddle.grad(g2, x)
    np.testing.assert_allclose(float(g3), 6.0)


def test_create_graph_mixed_partials():
    import numpy as np

    import paddle_tpu as paddle

    x = paddle.to_tensor(np.float32(1.1), stop_gradient=False)
    y = paddle.to_tensor(np.float32(0.7), stop_gradient=False)
    loss = x * y + paddle.sin(x)
    (gx,) = paddle.grad(loss, x, create_graph=True)
    np.testing.assert_allclose(float(gx), 0.7 + np.cos(1.1), rtol=1e-6)
    (gxx,) = paddle.grad(gx, x, retain_graph=True)
    np.testing.assert_allclose(float(gxx), -np.sin(1.1), rtol=1e-6)
    loss2 = x * y + paddle.sin(x)
    (gx2,) = paddle.grad(loss2, x, create_graph=True)
    (gxy,) = paddle.grad(gx2, y)
    np.testing.assert_allclose(float(gxy), 1.0, rtol=1e-6)


def test_backward_create_graph_grad_carries_graph():
    import numpy as np

    import paddle_tpu as paddle

    w = paddle.to_tensor(np.float32(3.0), stop_gradient=False)
    (w * w).sum().backward(create_graph=True)
    assert not w.grad.stop_gradient
    (h,) = paddle.grad(w.grad, w)
    np.testing.assert_allclose(float(h), 2.0)


def test_create_graph_hessian_matmul():
    # f = sum((A v)^2) → H = 2 AᵀA; exercises the cached-vjp pure backward
    import numpy as np

    import paddle_tpu as paddle

    A_np = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    A = paddle.to_tensor(A_np)
    v = paddle.to_tensor(np.array([0.5, -1.0], np.float32),
                         stop_gradient=False)
    f = ((A @ v) ** 2).sum()
    (gv,) = paddle.grad(f, v, create_graph=True)
    rows = []
    for i in range(2):
        seed = np.zeros(2, np.float32)
        seed[i] = 1
        (hv,) = paddle.grad(gv, v, grad_outputs=paddle.to_tensor(seed),
                            retain_graph=True)
        rows.append(np.asarray(hv._value))
    np.testing.assert_allclose(np.stack(rows), 2 * A_np.T @ A_np, rtol=1e-5)


def test_create_graph_gradient_penalty_training_step():
    # the WGAN-GP-style use: grad-norm penalty differentiated into params
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(0)
    lin = nn.Linear(3, 1)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 3).astype(np.float32),
                         stop_gradient=False)
    out = lin(x).sum()
    (gx,) = paddle.grad(out, x, create_graph=True)
    penalty = (gx ** 2).sum()
    penalty.backward()
    # d penalty / d W = 2 * W broadcast over batch: check nonzero & finite
    gw = np.asarray(lin.weight.grad._value)
    w = np.asarray(lin.weight._value)
    np.testing.assert_allclose(gw, 2 * 4 * w, rtol=1e-5)
