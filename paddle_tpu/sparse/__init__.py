"""paddle_tpu.sparse — COO/CSR sparse tensors.

Analog of /root/reference/python/paddle/sparse/ (creation, unary/binary,
matmul) over the C++ SparseCooTensor/SparseCsrTensor
(paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h) and the sparse
kernel library (paddle/phi/kernels/sparse/, ~40K LoC).

TPU-native backing: ``jax.experimental.sparse.BCOO`` — XLA's batched-COO
format with native lowering for elementwise and sparse@dense matmul (the
role of the reference's sparse CUDA kernels). CSR creation converts to
BCOO; ``crows``/``cols`` views are recomputed on demand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "SparseTensor", "sparse_coo_tensor", "sparse_csr_tensor",
    "is_same_shape", "add", "subtract", "multiply", "divide", "matmul",
    "masked_matmul", "relu", "abs", "sqrt", "sin", "tanh", "pow",
    "transpose", "coalesce",
]


class SparseTensor:
    """Wrapper over BCOO carrying the paddle sparse API surface."""

    def __init__(self, bcoo: jsparse.BCOO, fmt="coo"):
        self._bcoo = bcoo
        self._fmt = fmt

    # ---- metadata
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def is_sparse_coo(self):
        return self._fmt == "coo"

    def is_sparse_csr(self):
        return self._fmt == "csr"

    # ---- views
    def indices(self):
        return Tensor._from_value(self._bcoo.indices.T)  # (ndim, nnz)

    def values(self):
        return Tensor._from_value(self._bcoo.data)

    def crows(self):
        assert self._fmt == "csr", "crows() requires CSR"
        rows = np.asarray(self._bcoo.indices[:, 0])
        nrows = self.shape[0]
        crows = np.zeros(nrows + 1, np.int64)
        for r in rows:
            crows[r + 1] += 1
        return Tensor(np.cumsum(crows))

    def cols(self):
        assert self._fmt == "csr", "cols() requires CSR"
        return Tensor._from_value(self._bcoo.indices[:, 1])

    # ---- conversions
    def to_dense(self):
        return Tensor._from_value(self._bcoo.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseTensor(self._bcoo, "coo")

    def to_sparse_csr(self):
        return SparseTensor(self._bcoo, "csr")

    def coalesce(self):
        return SparseTensor(self._bcoo.sum_duplicates(), self._fmt)

    # ---- arithmetic
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def __repr__(self):
        return (f"SparseTensor(format={self._fmt}, shape={self.shape}, "
                f"nnz={self.nnz}, dtype={self.dtype})")


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, SparseTensor):
        return x
    return jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    """Create a COO tensor (reference python/paddle/sparse/creation.py):
    ``indices`` is (ndim, nnz)."""
    idx = np.asarray(_val(indices)).astype(np.int32)
    vals = _val(values)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype

        vals = jnp.asarray(vals, to_jax_dtype(dtype))
    else:
        vals = jnp.asarray(vals)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseTensor(bcoo, "coo")


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    """Create a CSR tensor; stored as BCOO with CSR views."""
    crows = np.asarray(_val(crows)).astype(np.int64)
    cols = np.asarray(_val(cols)).astype(np.int64)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    indices = np.stack([rows, cols])
    st = sparse_coo_tensor(indices, values, shape, dtype)
    return SparseTensor(st._bcoo, "csr")


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _binary(x, y, op):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        out = op(x.to_dense()._value, y.to_dense()._value)
        return SparseTensor(jsparse.BCOO.fromdense(out), x._fmt)
    if isinstance(x, SparseTensor):
        return Tensor._from_value(op(x.to_dense()._value, _val(y)))
    return Tensor._from_value(op(_val(x), y.to_dense()._value))


def add(x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return SparseTensor((x._bcoo + y._bcoo).sum_duplicates(), x._fmt)
    return _binary(x, y, jnp.add)


def subtract(x, y):
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        neg = SparseTensor(
            jsparse.BCOO((-y._bcoo.data, y._bcoo.indices), shape=y._bcoo.shape),
            y._fmt)
        return add(x, neg)
    return _binary(x, y, jnp.subtract)


def multiply(x, y):
    if isinstance(x, SparseTensor) and np.isscalar(y):
        return SparseTensor(
            jsparse.BCOO((x._bcoo.data * y, x._bcoo.indices),
                         shape=x._bcoo.shape), x._fmt)
    return _binary(x, y, jnp.multiply)


def divide(x, y):
    if isinstance(x, SparseTensor) and np.isscalar(y):
        return multiply(x, 1.0 / y)
    return _binary(x, y, jnp.divide)


def matmul(x, y):
    """sparse @ dense (and sparse @ sparse via densify) — reference
    paddle.sparse.matmul over cusparse SpMM."""
    if isinstance(x, SparseTensor) and isinstance(y, (Tensor, jax.Array)):
        return Tensor._from_value(x._bcoo @ _val(y))
    if isinstance(x, (Tensor, jax.Array)) and isinstance(y, SparseTensor):
        return Tensor._from_value(_val(x) @ y._bcoo.todense())
    if isinstance(x, SparseTensor) and isinstance(y, SparseTensor):
        return Tensor._from_value(x._bcoo.todense() @ y._bcoo.todense())
    raise TypeError("matmul expects at least one SparseTensor")


def masked_matmul(x, y, mask: SparseTensor):
    """Dense@dense with sparse output pattern (reference masked_matmul /
    SDDMM)."""
    out = _val(x) @ _val(y)
    idx = mask._bcoo.indices
    vals = out[idx[:, 0], idx[:, 1]]
    return SparseTensor(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape),
                        mask._fmt)


def _unary(x, op):
    return SparseTensor(
        jsparse.BCOO((op(x._bcoo.data), x._bcoo.indices),
                     shape=x._bcoo.shape), x._fmt)


def relu(x):
    return _unary(x, jax.nn.relu)


def abs(x):
    return _unary(x, jnp.abs)


def sqrt(x):
    return _unary(x, jnp.sqrt)


def sin(x):
    return _unary(x, jnp.sin)


def tanh(x):
    return _unary(x, jnp.tanh)


def pow(x, factor):
    return _unary(x, lambda v: jnp.power(v, factor))


def transpose(x, perm):
    bcoo = x._bcoo.transpose(tuple(perm))
    return SparseTensor(bcoo, x._fmt)


def coalesce(x):
    return x.coalesce()
