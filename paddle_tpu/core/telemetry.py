"""Fleet-wide telemetry: labeled metrics, request tracing, flight recorder.

The reference ships a profiler surface (python/paddle/profiler/) but its
production observability lives out of tree. This module is that layer
built fleet-native for the serving stack: ONE process-local home for
everything a multi-process serving fleet needs to answer "what is the
fleet doing right now" and "what was it doing when it died":

* **Metrics registry** — ``Counter`` / ``Gauge`` / ``Histogram`` with
  label sets and a lock-cheap bump on hot paths. ``registry().snapshot()``
  is a plain-JSON view; ``to_prometheus()`` is the text exposition
  format. Snapshots MERGE (``merge_snapshots``): replicas publish theirs
  to the gang store on their heartbeat cadence (``models/remote.py
  replica_main``) and the router folds them into one
  ``ServingRouter.fleet_metrics()`` view — fleet-wide TTFT/queue-wait
  percentiles, tokens/s, per-replica breaker state, one call.
  ``core.resilience.bump_counter`` delegates here, so every historical
  resilience counter is a registry metric too (one source of truth).
* **Request tracing** — a trace id is minted at ``ServingRouter.submit``
  (and ``ServingFrontend.submit`` standalone), rides the RPC envelope
  into the replica process, and every layer records spans against it:
  admit, queue-wait, prefill, chunked-prefill, decode segments, retire,
  plus failover/hedge/takeover hops as instant events. Spans land in a
  bounded process-local sink and export as Chrome-trace JSON
  (``export_chrome_trace``); ``stitch_chrome_traces`` merges per-process
  dumps so a kill-mid-decode drill yields one readable timeline of the
  request hopping replicas. Span timestamps are wall-clock (the one
  sanctioned use: Chrome-trace times must share an epoch ACROSS
  processes); durations are measured on the monotonic clock.
* **Flight recorder** — a bounded ring of recent telemetry events
  (replica deaths, failovers, breaker transitions, poison retirements,
  leadership changes). ``dump(reason)`` writes a post-mortem JSON file
  (events + metrics snapshot + recent spans); it fires automatically on
  breaker trips, poison retirements, ``StaleLeaderError`` stand-downs,
  and replica SIGTERM — the multi-process drills leave debuggable
  artifacts instead of nothing. Dumps are capped per process.

``FLAGS_telemetry=0`` disables hot-path observation (tracing + metric
bumps on the serving path) for A/B overhead measurement — bench e5 gates
``telemetry_overhead_pct`` < 3% of active processing with it ON.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time

from .flags import define_flag, flag

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "registry", "counter", "gauge", "histogram", "merge_snapshots",
    "summary_from_snapshot", "enabled",
    "new_trace_id", "Tracer", "tracer", "span", "maybe_span",
    "trace_event",
    "export_chrome_trace", "stitch_chrome_traces",
    "FlightRecorder", "flight_recorder", "flight_dump", "reset_telemetry",
]

define_flag("FLAGS_telemetry", True,
            "Master switch for hot-path telemetry (request tracing + "
            "metric observation on the serving path). Registries and "
            "explicit dumps still work when off; bench e5 A/Bs this "
            "flag to gate telemetry_overhead_pct < 3%.")
define_flag("FLAGS_trace_buffer", 8192,
            "Bounded span-sink capacity (completed spans + instant "
            "events kept per process; oldest dropped first)")
define_flag("FLAGS_flight_events", 512,
            "Flight-recorder ring capacity (most recent telemetry "
            "events kept per process)")
define_flag("FLAGS_flight_dir", "",
            "Directory for flight-recorder post-mortem dumps (empty: "
            "$PADDLE_FLIGHT_DIR, else <tmpdir>/paddle_tpu_flight)")
define_flag("FLAGS_flight_max_dumps", 8,
            "Max automatic flight-recorder dumps per process (a breaker "
            "flapping in a tight loop must not fill the disk)")

# default histogram buckets: serving latencies span ~100us (a counter
# bump) to minutes (a cold warmup); seconds, log-ish spacing
_DEF_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
# per-histogram-series reservoir of recent raw samples: exact percentiles
# for the window health endpoints care about (buckets are the unbounded-
# horizon fallback and the merge/exposition format)
_RESERVOIR = 512
# cap on reservoir samples serialized into a published snapshot (the
# replica → store → router path must stay cheap on the wire)
_SNAPSHOT_SAMPLES = 128


def enabled() -> bool:
    """Hot paths check this before observing (one dict lookup)."""
    return bool(flag("FLAGS_telemetry"))


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items())) if labels else ()


def _series_name(name: str, key: tuple) -> str:
    if not key:
        return name
    inner = ",".join(f"{k}={v}" for k, v in key)
    return f"{name}{{{inner}}}"


class _Metric:
    kind = "metric"

    def __init__(self, name, doc=""):
        self.name = name
        self.doc = doc
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def series(self) -> dict:
        with self._lock:
            return dict(self._series)


class Counter(_Metric):
    """Monotonic counter with optional labels:
    ``counter("serving.requests_total").inc(status="ok")``."""

    kind = "counter"

    def inc(self, n=1, **labels):
        key = _label_key(labels)
        with self._lock:
            v = self._series.get(key, 0) + n
            self._series[key] = v
            return v

    def value(self, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""

    kind = "gauge"

    def set(self, v, **labels):
        with self._lock:
            self._series[_label_key(labels)] = v

    def inc(self, n=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def dec(self, n=1, **labels):
        self.inc(-n, **labels)

    def value(self, **labels):
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class _HistSeries:
    __slots__ = ("count", "sum", "buckets", "sample", "pcache")

    def __init__(self, n_buckets):
        self.count = 0
        self.sum = 0.0
        self.buckets = [0] * (n_buckets + 1)   # +inf bucket last
        self.sample = collections.deque(maxlen=_RESERVOIR)
        # (count, qs) -> percentile dict: health endpoints poll
        # summaries far more often than observations arrive (idle pump
        # loops, per-dispatch scoring), and the reservoir sort only
        # changes when count does
        self.pcache = None


class Histogram(_Metric):
    """Bucketed distribution + a bounded reservoir of recent raw samples
    (exact recent-window percentiles for health endpoints; the buckets
    are the mergeable/exportable long-horizon view)."""

    kind = "histogram"

    def __init__(self, name, doc="", buckets=None):
        super().__init__(name, doc)
        self.bounds = tuple(sorted(buckets)) if buckets else _DEF_BUCKETS

    def observe(self, v, **labels):
        v = float(v)
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.bounds))
            s.count += 1
            s.sum += v
            i = 0
            for b in self.bounds:
                if v <= b:
                    break
                i += 1
            s.buckets[i] += 1
            s.sample.append(v)

    def snapshot_series(self, max_samples=None) -> dict:
        """Serialize every series UNDER the metric lock — the reservoir
        deque mutates concurrently with publishers (a replica heartbeat
        thread snapshotting while the pump observes), and an unlocked
        ``list(deque)`` can raise mid-mutation, silently dropping the
        publish (or a flight dump) exactly when it matters."""
        with self._lock:
            return {
                key: {"count": s.count, "sum": s.sum,
                      "bounds": list(self.bounds),
                      "buckets": list(s.buckets),
                      "sample": (list(s.sample)[-max_samples:]
                                 if max_samples else list(s.sample))}
                for key, s in self._series.items()}

    def percentiles(self, qs=(50, 95, 99), **labels):
        """Percentiles over the recent-sample reservoir (exact), falling
        back to bucket interpolation when the reservoir is empty (e.g. a
        series reconstructed from a merged snapshot)."""
        qs = tuple(qs)
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s.count == 0:
                return {f"p{q}": 0.0 for q in qs}
            if s.pcache is not None and s.pcache[0] == (s.count, qs):
                return dict(s.pcache[1])
            sample = sorted(s.sample)
            if sample:
                out = {f"p{q}": sample[
                    min(int(len(sample) * q / 100.0), len(sample) - 1)]
                    for q in qs}
            else:
                out = {f"p{q}": _bucket_quantile(self.bounds, s.buckets,
                                                 s.count, q / 100.0)
                       for q in qs}
            s.pcache = ((s.count, qs), dict(out))
            return out

    def summary(self, qs=(50, 95, 99), **labels):
        out = self.percentiles(qs, **labels)
        with self._lock:
            s = self._series.get(_label_key(labels))
            out["count"] = s.count if s else 0
            out["mean"] = (s.sum / s.count) if s and s.count else 0.0
        return out


def _bucket_quantile(bounds, buckets, count, q):
    target = q * count
    acc = 0
    lo = 0.0
    for i, b in enumerate(bounds):
        nxt = acc + buckets[i]
        if nxt >= target:
            # linear interpolation inside the bucket
            frac = (target - acc) / buckets[i] if buckets[i] else 0.0
            return lo + frac * (b - lo)
        acc = nxt
        lo = b
    return bounds[-1] if bounds else 0.0


class MetricsRegistry:
    """Get-or-create home for the process's metrics. One global default
    (``registry()``); construct private ones in tests."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, name, cls, doc, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, doc, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, doc="") -> Counter:
        return self._get(name, Counter, doc)

    def gauge(self, name, doc="") -> Gauge:
        return self._get(name, Gauge, doc)

    def histogram(self, name, doc="", buckets=None) -> Histogram:
        return self._get(name, Histogram, doc, buckets=buckets)

    def metrics(self) -> dict:
        with self._lock:
            return dict(self._metrics)

    def reset(self):
        """Zero every metric's series IN PLACE: handles cached by hot
        paths (``telemetry.counter(...)`` held in a local) stay
        registered and valid — dropping the objects instead would leave
        cached handles accumulating invisibly outside the registry."""
        for m in self.metrics().values():
            with m._lock:
                m._series.clear()

    # ------------------------------------------------------- exposition

    def snapshot(self) -> dict:
        """Plain-JSON view: ``{"ts": wall, "counters": {series: v},
        "gauges": {series: v}, "histograms": {series: {count, sum,
        bounds, buckets, sample}}}``. Series names flatten labels as
        ``name{k=v,...}``. This is the wire format replicas publish to
        the gang store and ``merge_snapshots`` folds."""
        out = {"ts": time.time(),  # wall-clock: x-process snapshot age
               "counters": {}, "gauges": {}, "histograms": {}}
        for name, m in self.metrics().items():
            if m.kind == "counter":
                for key, v in m.series().items():
                    out["counters"][_series_name(name, key)] = v
            elif m.kind == "gauge":
                for key, v in m.series().items():
                    out["gauges"][_series_name(name, key)] = v
            else:
                rows = m.snapshot_series(max_samples=_SNAPSHOT_SAMPLES)
                for key, row in rows.items():
                    out["histograms"][_series_name(name, key)] = row
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (metric names sanitized to the
        ``[a-zA-Z_:][a-zA-Z0-9_:]*`` charset; label sets preserved)."""
        lines = []
        for name, m in sorted(self.metrics().items()):
            pname = _prom_name(name)
            if m.doc:
                lines.append(f"# HELP {pname} {m.doc}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind in ("counter", "gauge"):
                for key, v in sorted(m.series().items()):
                    lines.append(f"{pname}{_prom_labels(key)} {_num(v)}")
            else:
                for key, row in sorted(m.snapshot_series().items()):
                    acc = 0
                    for b, c in zip(m.bounds, row["buckets"]):
                        acc += c
                        lines.append(
                            f"{pname}_bucket"
                            f"{_prom_labels(key, le=repr(float(b)))} {acc}")
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(key, le='+Inf')} {row['count']}")
                    lines.append(
                        f"{pname}_sum{_prom_labels(key)} "
                        f"{_num(row['sum'])}")
                    lines.append(
                        f"{pname}_count{_prom_labels(key)} {row['count']}")
        return "\n".join(lines) + "\n"


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _prom_labels(key: tuple, **extra) -> str:
    items = [f'{k}="{v}"' for k, v in key] + [
        f'{k}="{v}"' for k, v in extra.items()]
    return "{" + ",".join(items) + "}" if items else ""


def _num(v):
    return int(v) if isinstance(v, float) and v.is_integer() else v


def merge_snapshots(*snapshots) -> dict:
    """Fold N ``MetricsRegistry.snapshot()`` dicts into one fleet view:
    counters and histogram counts/sums/buckets SUM, gauges keep the
    freshest snapshot's value, reservoir samples concatenate (bounded).
    The router's ``fleet_metrics()`` runs this over its own snapshot +
    every replica's store-published one."""
    out = {"ts": 0.0, "counters": {}, "gauges": {}, "histograms": {}}
    for snap in snapshots:
        if not snap:
            continue
        ts = float(snap.get("ts", 0.0))
        for k, v in snap.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in snap.get("gauges", {}).items():
            prev = out["gauges"].get(k)
            if prev is None or ts >= out["ts"]:
                out["gauges"][k] = v
        for k, h in snap.get("histograms", {}).items():
            cur = out["histograms"].get(k)
            if cur is None:
                out["histograms"][k] = {
                    "count": h["count"], "sum": h["sum"],
                    "bounds": list(h["bounds"]),
                    "buckets": list(h["buckets"]),
                    "sample": list(h.get("sample", ()))[-_RESERVOIR:],
                }
            else:
                cur["count"] += h["count"]
                cur["sum"] += h["sum"]
                if (cur["buckets"] is not None
                        and list(cur["bounds"]) == list(h["bounds"])):
                    cur["buckets"] = [a + b for a, b in
                                      zip(cur["buckets"], h["buckets"])]
                elif cur["buckets"] is not None:
                    # mixed bucket layouts (custom buckets= in one
                    # process, mixed code versions in a rolling fleet):
                    # summing incompatible buckets under summed counts
                    # would yield silently-wrong interpolated
                    # percentiles — invalidate the buckets (the merged
                    # reservoir still answers percentiles) and count it
                    cur["buckets"] = None
                    counter("telemetry.merge_bounds_mismatch").inc()
                cur["sample"] = (cur["sample"]
                                 + list(h.get("sample", ())))[-_RESERVOIR:]
        out["ts"] = max(out["ts"], ts)
    return out


def summary_from_snapshot(snapshot, name, qs=(50, 95, 99)) -> dict:
    """Percentile summary for one histogram series out of a (possibly
    merged) snapshot — reservoir when present, bucket interpolation
    otherwise. Returns zeros for an unknown/empty series."""
    h = (snapshot or {}).get("histograms", {}).get(name)
    if not h or not h.get("count"):
        return {f"p{q}": 0.0 for q in qs} | {"count": 0, "mean": 0.0}
    sample = sorted(h.get("sample", ()))
    if sample:
        out = {f"p{q}": sample[min(int(len(sample) * q / 100.0),
                                   len(sample) - 1)] for q in qs}
    elif h.get("buckets"):  # None after a bounds-mismatched merge
        out = {f"p{q}": _bucket_quantile(tuple(h["bounds"]), h["buckets"],
                                         h["count"], q / 100.0)
               for q in qs}
    else:
        out = {f"p{q}": 0.0 for q in qs}
    out["count"] = h["count"]
    out["mean"] = h["sum"] / h["count"]
    return out


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _registry


def counter(name, doc="") -> Counter:
    return _registry.counter(name, doc)


def gauge(name, doc="") -> Gauge:
    return _registry.gauge(name, doc)


def histogram(name, doc="", buckets=None) -> Histogram:
    return _registry.histogram(name, doc, buckets=buckets)


# ============================================================== tracing

_trace_counter = [0]
_trace_lock = threading.Lock()


def new_trace_id() -> str:
    """Process-unique trace id: pid-tagged so ids minted by different
    fleet processes can never collide in a stitched timeline."""
    with _trace_lock:
        _trace_counter[0] += 1
        n = _trace_counter[0]
    return f"{os.getpid():x}-{int(time.time() * 1e3) & 0xFFFFFFFF:08x}-{n:x}"  # wall-clock: x-process id salt


class _SpanHandle:
    """Context manager for one in-flight span; records into the sink on
    exit. ``event(name)`` adds an instant event under the same trace."""

    __slots__ = ("_tracer", "name", "trace", "rid", "args", "_t0w", "_t0m")

    def __init__(self, tr, name, trace, rid, args):
        self._tracer = tr
        self.name = name
        self.trace = trace
        self.rid = rid
        self.args = args

    def __enter__(self):
        self._t0w = time.time()  # wall-clock: x-process trace epoch
        self._t0m = time.monotonic()
        return self

    def event(self, name, **args):
        self._tracer.event(name, trace=self.trace, rid=self.rid, **args)

    def __exit__(self, *exc):
        dur = time.monotonic() - self._t0m
        self._tracer.add_span(self.name, self._t0w, dur,
                              trace=self.trace, rid=self.rid, **self.args)
        return False


class Tracer:
    """Bounded process-local span sink. Completed spans are stored
    directly as Chrome-trace events (``ph:"X"`` slices, ``ph:"i"``
    instants) stamped with this process's pid and wall-clock
    microseconds, so export is a dump and cross-process stitching is a
    concatenation."""

    def __init__(self, capacity=None):
        # capacity=None follows FLAGS_trace_buffer at APPEND time (the
        # global sink is built at import, before an operator's
        # set_flags can run — a pinned-at-import capacity would make
        # the flag silently inert); an explicit capacity pins it.
        self._capacity = capacity
        cap = int(capacity if capacity is not None
                  else flag("FLAGS_trace_buffer"))
        self._events = collections.deque(maxlen=max(cap, 16))
        self._lock = threading.Lock()

    def _resize(self):
        """Caller holds the lock. Re-reads the capacity flag and
        rebuilds the ring when the operator changed it."""
        if self._capacity is not None:
            return
        cap = max(int(flag("FLAGS_trace_buffer")), 16)
        if cap != self._events.maxlen:
            self._events = collections.deque(self._events, maxlen=cap)

    def span(self, name, trace=None, rid=None, **args) -> _SpanHandle:
        return _SpanHandle(self, name, trace, rid, args)

    def add_span(self, name, start_wall_s, dur_s, trace=None, rid=None,
                 **args):
        """Record a completed span retroactively (queue-wait spans are
        only known at admission time)."""
        a = dict(args)
        if trace is not None:
            a["trace"] = trace
        if rid is not None:
            a["rid"] = rid
        ev = {"name": name, "ph": "X", "pid": os.getpid(),
              "tid": threading.get_ident() & 0xFFFF,
              "ts": start_wall_s * 1e6, "dur": max(dur_s, 0.0) * 1e6,
              "args": a}
        with self._lock:
            self._resize()
            self._events.append(ev)

    def event(self, name, trace=None, rid=None, **args):
        a = dict(args)
        if trace is not None:
            a["trace"] = trace
        if rid is not None:
            a["rid"] = rid
        ev = {"name": name, "ph": "i", "s": "p", "pid": os.getpid(),
              "tid": threading.get_ident() & 0xFFFF,
              "ts": time.time() * 1e6, "args": a}  # wall-clock: x-process trace epoch
        with self._lock:
            self._resize()
            self._events.append(ev)

    def spans(self, name=None, trace=None) -> list:
        """Recorded events, optionally filtered by span name and/or the
        trace id carried in ``args`` (including rid-batched spans whose
        ``args['traces']`` LIST contains it)."""
        with self._lock:
            evs = list(self._events)
        if name is not None:
            evs = [e for e in evs if e["name"] == name]
        if trace is not None:
            evs = [e for e in evs
                   if e.get("args", {}).get("trace") == trace
                   or trace in (e.get("args", {}).get("traces") or ())]
        return evs

    def clear(self):
        with self._lock:
            self._events.clear()

    def export_chrome_trace(self, path, extra_events=()) -> str:
        """Write the sink as chrome://tracing / Perfetto JSON."""
        evs = self.spans() + list(extra_events)
        with open(path, "w") as f:
            json.dump({"traceEvents": evs,
                       "displayTimeUnit": "ms"}, f)
        return path


_tracer = Tracer()


def tracer() -> Tracer:
    return _tracer


def span(name, trace=None, rid=None, **args) -> _SpanHandle:
    return _tracer.span(name, trace=trace, rid=rid, **args)


def trace_event(name, trace=None, rid=None, **args):
    _tracer.event(name, trace=trace, rid=rid, **args)


def export_chrome_trace(path, extra_events=()) -> str:
    return _tracer.export_chrome_trace(path, extra_events=extra_events)


def stitch_chrome_traces(paths, out_path) -> str:
    """Merge per-process Chrome-trace dumps (router + replicas) into one
    timeline file. Events already carry distinct pids and share the
    wall-clock epoch, so stitching is concatenation + a time sort;
    unreadable inputs are skipped (a SIGKILLed replica never wrote
    one)."""
    events = []
    for p in paths:
        try:
            with open(p) as f:
                events.extend(json.load(f).get("traceEvents", []))
        except (OSError, ValueError):
            continue
    events.sort(key=lambda e: e.get("ts", 0))
    with open(out_path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)
    return out_path


# ====================================================== flight recorder

class FlightRecorder:
    """Bounded ring of recent telemetry events + post-mortem dumps.

    ``record(kind, **payload)`` is always-on and cheap (one deque append
    under a lock). ``dump(reason)`` writes events + a metrics snapshot +
    the tail of the span sink to a JSON file and returns its path —
    called automatically on breaker trips (``core.resilience``), poison
    retirements (``models/serving``), stale-leader stand-downs
    (``models/router``) and replica SIGTERM (``models/remote``), capped
    at ``FLAGS_flight_max_dumps`` per process."""

    def __init__(self, capacity=None):
        # capacity=None follows FLAGS_flight_events at append time
        # (mirror of Tracer: the global ring exists before set_flags
        # can run); an explicit capacity pins it
        self._capacity = capacity
        cap = int(capacity if capacity is not None
                  else flag("FLAGS_flight_events"))
        self._events = collections.deque(maxlen=max(cap, 16))
        self._lock = threading.Lock()
        self._dumps = 0

    def record(self, kind, **payload):
        ev = {"ts": time.time(), "kind": str(kind), **payload}  # wall-clock: x-process post-mortems
        with self._lock:
            if self._capacity is None:
                cap = max(int(flag("FLAGS_flight_events")), 16)
                if cap != self._events.maxlen:
                    self._events = collections.deque(self._events,
                                                     maxlen=cap)
            self._events.append(ev)

    def events(self, kind=None) -> list:
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dumps = 0

    @staticmethod
    def dump_dir() -> str:
        d = flag("FLAGS_flight_dir") or os.environ.get("PADDLE_FLIGHT_DIR")
        if not d:
            import tempfile

            d = os.path.join(tempfile.gettempdir(), "paddle_tpu_flight")
        return d

    def dump(self, reason, path=None, force=False):
        """Write the post-mortem file; returns its path, or None when the
        per-process auto-dump cap was reached (``force=True`` — an
        operator asking explicitly — bypasses the cap). Never raises:
        a full disk must not mask the failure being recorded."""
        with self._lock:
            if not force and self._dumps >= int(
                    flag("FLAGS_flight_max_dumps")):
                counter("telemetry.flight_dump_skipped").inc()
                return None
            self._dumps += 1
            seq = self._dumps
            evs = list(self._events)
        try:
            if path is None:
                d = self.dump_dir()
                os.makedirs(d, exist_ok=True)
                safe = "".join(c if (c.isalnum() or c in "._-") else "_"
                               for c in str(reason))[:80]
                path = os.path.join(
                    d, f"flight-{os.getpid()}-{seq:03d}-{safe}.json")
            payload = {
                "reason": str(reason),
                "pid": os.getpid(),
                "ts": time.time(),  # wall-clock: x-process post-mortems
                "events": evs,
                "metrics": _registry.snapshot(),
                "spans": _tracer.spans()[-256:],
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
            counter("telemetry.flight_dump").inc()
            return path
        except Exception:  # noqa: BLE001 — the dump is best-effort
            # forensics; failing it must not mask the original failure
            counter("telemetry.flight_dump_error").inc()
            return None


_flight = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _flight


def flight_dump(reason, **event):
    """Record one event and dump the recorder — the one-liner the
    trigger sites call."""
    if event:
        _flight.record(reason, **event)
    return _flight.dump(reason)


def reset_telemetry():
    """Test teardown: clear the registry, the span sink, and the flight
    ring (re-arms the per-process dump cap)."""
    _registry.reset()
    _tracer.clear()
    _flight.clear()


class _NoopSpan:
    """Stands in for a span when telemetry is off: same surface, no
    recording, shared instance (no per-call allocation on hot paths)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def event(self, name, **args):
        pass


NOOP_SPAN = _NoopSpan()


def maybe_span(name, trace=None, rid=None, **args):
    """``span(...)`` when telemetry is enabled, else the shared no-op —
    the form hot paths use so a disabled registry costs one flag read."""
    if not enabled():
        return NOOP_SPAN
    return _tracer.span(name, trace=trace, rid=rid, **args)
