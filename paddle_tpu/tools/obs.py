"""``python -m paddle_tpu.tools.obs`` — the operator's observability CLI.

Subcommands over the artifacts the telemetry/perfwatch layers leave on
disk (and the live process registry, for REPL use):

* ``metrics [PATH]`` — pretty-print a metrics snapshot: counters,
  gauges, and percentile summaries for every histogram. ``PATH`` is a
  ``MetricsRegistry.snapshot()`` JSON (a replica's store-published
  snapshot saved to a file, or a flight dump — its embedded snapshot is
  used); with no PATH the CURRENT process registry prints (useful from
  a REPL or a debug hook, not across processes).
* ``flights [--dir D] [-n N]`` / ``flights PATH`` — tail the flight-
  recorder dumps: with no PATH, list the N most recent dumps in the
  flight dir (``FLAGS_flight_dir`` → ``$PADDLE_FLIGHT_DIR`` →
  ``<tmpdir>/paddle_tpu_flight``) with reason/age/event counts; with a
  PATH, inspect one dump (event ring tail, span tail, key metrics).
* ``slo [PATH]`` — the overload-control view: SLO burn-rate windows
  (the ``slo.*`` gauges the monitor exports), brownout ladder stage and
  transitions, the shed/reject counters with their ``{tenant,
  priority}`` attribution, and the autoscaler/brownout decision history
  (flight events) — from the live process or any snapshot/flight dump.
* ``kv [PATH]`` — the paged-KV allocator view: page-pool occupancy and
  free-list headroom (``serving.kv_pages_free`` / ``kv_pages_total``),
  fragmentation (allocated-but-unused granted tail), prefix-cache hit
  rate and tokens saved, pool-pressure counters (admission deferrals,
  preemptions), and per-slot granted page counts
  (``serving.kv_slot_pages{slot=}``) — from the live process or any
  snapshot/flight dump.
* ``fleet [PATH]`` — the membership view: per-replica (and per-TP-group)
  state, breaker, assignment, last-heartbeat age, and incarnation from
  the ``fleet.replica_*`` / ``tp.*`` series the router and group members
  export, plus the death / lease / takeover event history — from the
  live process or any snapshot/flight dump (the offline path matters:
  the live router is exactly the thing that died).
* ``bench-diff A B`` — metric-by-metric comparison of two ``BENCH_*``
  records (round files or the baseline), flagging the big movers. The
  full series harness is ``tools/bench_trend.py``.
* ``lint [REPORT.json | paths...]`` — render a tpu-lint ``--json``
  report (or run the analyzer in-process over paths) as the table
  incident runbooks and CI logs share: findings by rule/site, the
  jit-entry inventory, and the fleet lock graph with its ordering
  edges and any cycles. The analyzer itself is
  ``python -m paddle_tpu.tools.analyze``.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

from . import bench_trend as _bt


def _fmt_num(v):
    if isinstance(v, float):
        if v and (abs(v) < 1e-3 or abs(v) >= 1e7):
            return f"{v:.3e}"
        return f"{v:,.4f}".rstrip("0").rstrip(".")
    return f"{v:,}"


def _print_snapshot(snap, out=None):
    # resolve sys.stdout at CALL time: binding it as a def-time default
    # captures whatever stream was installed when the module was first
    # imported (e.g. a test harness's since-closed capture)
    out = out if out is not None else sys.stdout
    from ..core import telemetry

    ts = snap.get("ts")
    if ts:
        age = max(time.time() - float(ts), 0.0)  # wall-clock: snapshot age
        out.write(f"snapshot age: {age:.1f}s\n")
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    if counters:
        out.write(f"\ncounters ({len(counters)}):\n")
        for k in sorted(counters):
            out.write(f"  {k:<56} {_fmt_num(counters[k])}\n")
    if gauges:
        out.write(f"\ngauges ({len(gauges)}):\n")
        for k in sorted(gauges):
            out.write(f"  {k:<56} {_fmt_num(gauges[k])}\n")
    if hists:
        out.write(f"\nhistograms ({len(hists)}):\n")
        for k in sorted(hists):
            s = telemetry.summary_from_snapshot(snap, k)
            out.write(
                f"  {k:<44} n={s['count']:<8} mean={s['mean']:.6g} "
                f"p50={s['p50']:.6g} p95={s['p95']:.6g} "
                f"p99={s['p99']:.6g}\n")
    if not (counters or gauges or hists):
        out.write("(empty snapshot)\n")


def _load_snapshot(path):
    """Load a metrics snapshot from ``path`` — either a bare
    ``MetricsRegistry.snapshot()`` JSON or a flight dump (whose embedded
    snapshot and event ring are unwrapped). Returns ``(snap, events)``
    (``events`` is None for a bare snapshot) or ``None`` after writing
    the error to stderr — the caller returns 2. ONE loader for every
    subcommand, so a dump-format tweak lands in one place."""
    try:
        obj = json.load(open(path))
    except (OSError, ValueError) as e:
        sys.stderr.write(f"cannot read {path}: {e}\n")
        return None
    if isinstance(obj, dict) and "metrics" in obj:    # a flight dump
        snap, events = obj.get("metrics") or {}, obj.get("events", [])
    else:                                             # a bare snapshot
        snap, events = obj, None
    if not isinstance(snap, dict) or not (
            {"counters", "gauges", "histograms"} & set(snap)):
        sys.stderr.write(
            f"{path} is not a metrics snapshot or flight dump\n")
        return None
    return snap, events


def cmd_metrics(args) -> int:
    from ..core import telemetry

    if args.path:
        loaded = _load_snapshot(args.path)
        if loaded is None:
            return 2
        snap, _ = loaded
    else:
        snap = telemetry.registry().snapshot()
    _print_snapshot(snap)
    return 0


def _flight_dir(args):
    if args.dir:
        return args.dir
    from ..core.telemetry import FlightRecorder

    return FlightRecorder.dump_dir()


def cmd_flights(args) -> int:
    if args.path:
        return _inspect_flight(args.path)
    d = _flight_dir(args)
    paths = sorted(glob.glob(os.path.join(d, "flight-*.json")),
                   key=os.path.getmtime, reverse=True)
    if not paths:
        print(f"no flight dumps under {d}")
        return 0
    print(f"{len(paths)} dump(s) under {d} (newest first):")
    for p in paths[:args.n]:
        try:
            obj = json.load(open(p))
        except (OSError, ValueError):
            print(f"  {os.path.basename(p):<52} <unreadable>")
            continue
        age = max(time.time() - obj.get("ts", 0), 0.0)  # wall-clock: dump age
        kinds = {}
        for e in obj.get("events", []):
            kinds[e.get("kind")] = kinds.get(e.get("kind"), 0) + 1
        top = ",".join(f"{k}x{n}" for k, n in sorted(
            kinds.items(), key=lambda kv: -kv[1])[:3])
        print(f"  {os.path.basename(p):<52} {age:8.0f}s ago  "
              f"reason={obj.get('reason')}  events={top or '-'}")
    return 0


def _inspect_flight(path) -> int:
    try:
        obj = json.load(open(path))
    except (OSError, ValueError) as e:
        sys.stderr.write(f"cannot read {path}: {e}\n")
        return 2
    print(f"reason : {obj.get('reason')}")
    print(f"pid    : {obj.get('pid')}")
    evs = obj.get("events", [])
    print(f"events : {len(evs)} (tail)")
    for e in evs[-20:]:
        extra = {k: v for k, v in e.items() if k not in ("ts", "kind")}
        print(f"  {e.get('kind', '?'):<24} {extra}")
    spans = obj.get("spans", [])
    print(f"spans  : {len(spans)} recorded (tail)")
    for s in spans[-10:]:
        dur = s.get("dur")
        print(f"  {s.get('name', '?'):<32} "
              f"{'%0.3fms' % (dur / 1e3) if dur is not None else 'event'}")
    snap = obj.get("metrics")
    if isinstance(snap, dict):
        print("\nembedded metrics snapshot:")
        _print_snapshot(snap)
    return 0


def cmd_slo(args) -> int:
    """Overload-control view: burn-rate windows, brownout stage, shed
    counts, and the autoscaler's decision history — from the live
    process (registry + flight ring) or a snapshot/flight-dump file."""
    from ..core import telemetry

    events = None
    if args.path:
        loaded = _load_snapshot(args.path)
        if loaded is None:
            return 2
        snap, events = loaded
    else:
        snap = telemetry.registry().snapshot()
        events = [{"kind": e["kind"],
                   **{k: v for k, v in e.items() if k != "kind"}}
                  for e in telemetry.flight_recorder().events()]
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})

    # --- burn-rate windows (slo.* gauges set by SLOMonitor.status())
    alarm = gauges.get("slo.alarm")
    print(f"slo alarm : "
          f"{'UP' if alarm else 'clear' if alarm is not None else '(no evaluation recorded)'}")
    burns = sorted(k for k in gauges if k.startswith("slo.burn{"))
    if burns:
        print("burn rate (error budget burn per objective/window):")
        for k in burns:
            labels = _labels_of(k)
            gkey = f"slo.goodput{{{k.split('{', 1)[1]}"
            gp = gauges.get(gkey)
            print(f"  {labels.get('objective', '?'):<16} "
                  f"{labels.get('window', '?'):>8}  "
                  f"burn={gauges[k]:<8g} "
                  f"goodput={gp if gp is not None else '-'}")

    # --- brownout ladder
    stage = gauges.get("serving.brownout_stage", 0)
    ups = sum(v for k, v in counters.items()
              if k.startswith("serving.brownout_transitions")
              and "direction=up" in k)
    downs = sum(v for k, v in counters.items()
                if k.startswith("serving.brownout_transitions")
                and "direction=down" in k)
    print(f"brownout  : stage {int(stage)} "
          f"({ups} escalation(s), {downs} recover(ies))")

    # --- shed / reject accounting (labeled {tenant, priority} series)
    fams = ("serving.shed", "serving.rejected", "serving.slo_shed",
            "serving.quota_rejected", "serving.brownout_shed")
    rows = [(k, v) for k, v in sorted(counters.items())
            if k.split("{", 1)[0] in fams]
    if rows:
        print("shed/reject counters:")
        for k, v in rows:
            print(f"  {k:<56} {v}")

    # --- replicas + autoscaler decisions (flight events)
    reps = gauges.get("fleet.replicas_up")
    if reps is not None:
        print(f"replicas  : {int(reps)} up")
    decisions = [e for e in (events or ())
                 if str(e.get("kind", "")).startswith(("autoscale.",
                                                       "brownout"))]
    if decisions:
        print(f"decision history ({len(decisions)} event(s), "
              "oldest first):")
        for e in decisions[-args.n:]:
            extra = {k: v for k, v in e.items()
                     if k not in ("kind", "ts")}
            print(f"  {e.get('kind'):<24} {extra}")
    elif events is not None:
        print("decision history: (no autoscaler/brownout events "
              "recorded)")
    return 0


def cmd_kv(args) -> int:
    """Paged-KV allocator view: page-pool occupancy, fragmentation,
    prefix-cache hit rate, and per-slot granted page counts — from the
    live process or a snapshot/flight-dump file."""
    from ..core import perfwatch, telemetry

    if args.path:
        loaded = _load_snapshot(args.path)
        if loaded is None:
            return 2
        snap, _ = loaded
    else:
        snap = telemetry.registry().snapshot()
    kv = perfwatch.kv_pool_summary(snap)
    total, free = kv["pages_total"], kv["pages_free"]
    if total is None:
        print("kv pool   : (no serving.kv_pages_total gauge recorded — "
              "no engine ran with telemetry on)")
    else:
        used = int(total) - int(free or 0)
        width = 30
        fill = int(round(width * used / total)) if total else 0
        print(f"kv pool   : {used}/{int(total)} pages granted "
              f"[{'#' * fill}{'.' * (width - fill)}] "
              f"({int(free or 0)} free)")
    if kv.get("pages_pinned_export"):
        print(f"pinned    : {int(kv['pages_pinned_export'])} page(s) "
              "pinned for export (prefill done, awaiting transfer)")
    if kv["bytes_in_use"] is not None:
        print(f"kv bytes  : {_fmt_num(kv['bytes_in_use'])} in use "
              f"(page-granular, active slots)")
    if kv["fragmentation_pct"] is not None:
        print(f"frag      : {kv['fragmentation_pct']:.1f}% "
              "allocated-but-unused tail of granted pages")
    if kv["slot_occupancy"] is not None:
        print(f"slots     : {kv['slot_occupancy']:.2f} occupancy")
    hr = kv["prefix_hit_rate"]
    print(f"prefix    : hit rate "
          f"{hr if hr is None else format(hr, '.3f')}, "
          f"{int(kv['prefix_tokens_saved'])} prompt token(s) saved")
    print(f"pressure  : {int(kv['pool_exhausted'])} admission "
          f"deferral(s), {int(kv['preempted'])} preemption(s)")
    if kv["slot_pages"]:
        print("per-slot granted pages:")
        for slot in sorted(kv["slot_pages"]):
            n = kv["slot_pages"][slot]
            print(f"  slot {slot:<4} {n:>5}  {'#' * min(n, 40)}")
    return 0


def _labels_of(key):
    """``name{k=v,k2=v2}`` → dict of labels (the snapshot's flattened
    series-key format)."""
    if "{" not in key:
        return {}
    return dict(p.split("=", 1)
                for p in key.split("{", 1)[1][:-1].split(",") if "=" in p)


def cmd_fleet(args) -> int:
    """Fleet membership view: per-replica state / breaker / assignment /
    heartbeat age / incarnation from the ``fleet.replica_*`` gauges the
    router exports, per-TP-group membership from the ``tp.*`` series,
    and the death / lease / takeover event history — from the live
    process (registry + flight ring) or any snapshot / flight dump."""
    from ..core import telemetry

    events = None
    if args.path:
        loaded = _load_snapshot(args.path)
        if loaded is None:
            return 2
        snap, events = loaded
    else:
        snap = telemetry.registry().snapshot()
        events = telemetry.flight_recorder().events()
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})

    # --- per-replica roster (fleet.replica_* labeled gauges)
    state_names = {1: "up", 2: "draining", 0: "dead"}
    breaker_names = {0: "closed", 1: "half-open", 2: "open"}
    rows: dict[str, dict] = {}
    for k, v in gauges.items():
        fam = k.split("{", 1)[0]
        if not fam.startswith("fleet.replica_"):
            continue
        labels = _labels_of(k)
        rep = labels.get("replica")
        if rep is None:
            continue
        row = rows.setdefault(rep, {})
        if fam == "fleet.replica_incarnation":
            row["inc"] = labels.get("inc", "?")
        elif fam == "fleet.replica_role":
            row["role"] = labels.get("role", "?")
        else:
            row[fam[len("fleet.replica_"):]] = v
    if rows:
        print(f"replicas ({len(rows)}):")
        print(f"  {'id':<6} {'state':<9} {'role':<8} {'breaker':<10} "
              f"{'assigned':>8} {'served':>7} {'hb age':>8}  inc")
        for rep in sorted(rows, key=lambda r: (len(r), r)):
            row = rows[rep]
            hb = row.get("hb_age_s")
            print(f"  {rep:<6} "
                  f"{state_names.get(row.get('state'), '?'):<9} "
                  f"{row.get('role', '-'):<8} "
                  f"{breaker_names.get(row.get('breaker'), '?'):<10} "
                  f"{int(row.get('assigned', 0)):>8} "
                  f"{int(row.get('served', 0)):>7} "
                  f"{(f'{hb:.2f}s' if hb is not None else '-'):>8}  "
                  f"{row.get('inc', '-')}")
    else:
        print("replicas: (no fleet.replica_* gauges recorded — the "
              "router exports them at every fleet_metrics() call)")

    # --- live page-transfer tickets (fleet.transfer_ticket flips to 0
    # when the handoff completes, so value==1 means mid-flight)
    tickets = [(lab.get("rid", "?"), lab.get("ticket", "?"),
                lab.get("src", "?"))
               for k, v in gauges.items()
               if k.split("{", 1)[0] == "fleet.transfer_ticket"
               and v == 1
               for lab in (_labels_of(k),)]
    inflight = gauges.get("fleet.transfer_inflight")
    if tickets:
        print(f"transfers in flight ({len(tickets)}):")
        for rid, tid, src in sorted(tickets):
            print(f"  rid {rid:<6} ticket {tid:<10} source {src}")
    elif inflight:
        print(f"transfers in flight: {int(inflight)} "
              "(no ticket gauges in this snapshot)")

    # --- TP groups (tp.* series from the group member processes)
    groups = {_labels_of(k).get("group", "?"): v
              for k, v in gauges.items()
              if k.split("{", 1)[0] == "tp.group_members" and "{" in k}
    degree = gauges.get("tp.engine_degree")
    if groups or degree is not None:
        print("tp groups:")
        if degree is not None:
            print(f"  engine TP degree: {int(degree)}")
        for g in sorted(groups):
            print(f"  group {g}: {int(groups[g])} member(s)")
        for name in ("tp.member_dead", "tp.collective_timeout",
                     "tp.group_collapsed", "tp.member_rejoined",
                     "tp.group_form_timeout", "tp.member_store_lost"):
            if counters.get(name):
                print(f"  {name:<24} {counters[name]}")

    # --- death / lease / takeover history (flight events)
    fams = ("replica_dead", "tp_member_death", "takeover",
            "lease_acquired", "lease_superseded", "stand_down",
            "failover")
    history = [e for e in (events or ())
               if str(e.get("kind", "")) in fams]
    if history:
        shown = min(args.n, len(history))
        print(f"event history (last {shown} of {len(history)} event(s), "
              "oldest first):")
        for e in history[-args.n:]:
            extra = {k: v for k, v in e.items()
                     if k not in ("kind", "ts")}
            print(f"  {e.get('kind'):<18} {extra}")
    elif events is not None:
        print("event history: (no membership events recorded)")
    return 0


def cmd_lint(args) -> int:
    """Render tpu-lint output as a table: from a ``--json`` report file
    when the one argument is a .json path, else by running the analyzer
    in-process over the given paths (default: the installed package)."""
    from . import analyze

    if len(args.paths) == 1 and args.paths[0].endswith(".json"):
        try:
            report = json.load(open(args.paths[0]))
        except (OSError, ValueError) as e:
            sys.stderr.write(f"cannot read {args.paths[0]}: {e}\n")
            return 2
        if "findings" not in report or "lock_graph" not in report:
            sys.stderr.write(
                f"{args.paths[0]} is not a tpu-lint --json report\n")
            return 2
    else:
        paths = args.paths or analyze._default_paths()
        try:
            report, _ = analyze.make_report(paths)
        except (OSError, ValueError, SyntaxError) as e:
            sys.stderr.write(f"tpu-lint: {e}\n")
            return 2

    findings = report.get("findings", [])
    if findings:
        print(f"findings ({len(findings)}):")
        print(f"  {'severity':<9} {'rule':<28} {'site':<40} why")
        for f in findings:
            site = f"{f['path']}:{f['line']}"
            print(f"  {f.get('severity', 'error'):<9} {f['rule']:<28} "
                  f"{site:<40} {f['why']}")
            if f.get("hint"):
                print(f"  {'':<9} {'':<28} {'':<40} hint: {f['hint']}")
    else:
        print("findings: none")
    sup = report.get("suppressed", {})
    if sup.get("pragma") or sup.get("baseline"):
        print(f"suppressed: {sup.get('pragma', 0)} by pragma, "
              f"{sup.get('baseline', 0)} by baseline")
    entries = report.get("jit_entries", [])
    print(f"\njit entries ({len(entries)}):")
    for e in entries:
        print(f"  {e['wrapper']:<12} {e['path']}:{e['line']:<5} "
              f"{e['name']}")
    lg = report.get("lock_graph", {})
    locks = lg.get("locks", {})
    print(f"\nlock graph ({len(locks)} lock(s), "
          f"{len(lg.get('edges', []))} ordering edge(s)):")
    for lid in sorted(locks):
        li = locks[lid]
        print(f"  {li['kind']:<10} {lid}")
    for e in lg.get("edges", []):
        print(f"  order: {e['from']} -> {e['to']} "
              f"({e['path']}:{e['line']})")
    cycles = lg.get("cycles", [])
    if cycles:
        print(f"  CYCLES ({len(cycles)} — deadlock risk):")
        for c in cycles:
            print(f"    {' -> '.join(c + [c[0]])}")
    else:
        print("  cycles: none")
    return 1 if findings else 0


def cmd_bench_diff(args) -> int:
    try:
        rows = _bt.diff_rounds(args.a, args.b)
    except (OSError, ValueError) as e:
        sys.stderr.write(f"bench-diff failed: {e}\n")
        return 2
    if not rows:
        print("no shared metrics between the two records")
        return 0
    a_name = os.path.basename(args.a)
    b_name = os.path.basename(args.b)
    print(f"{'metric':<44} {a_name:>16} {b_name:>16} {'ratio':>8}")
    movers = 0
    for metric, a, b, ratio in rows:
        mark = ""
        if ratio is not None and (ratio < 1 / args.factor
                                  or ratio > args.factor):
            mark = "  <-- "
            movers += 1
        print(f"{metric:<44} {a:>16g} {b:>16g} "
              f"{ratio if ratio is None else round(ratio, 3)!s:>8}{mark}")
    print(f"\n{movers} metric(s) moved beyond {args.factor}x")
    return 1 if movers else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.tools.obs",
        description="Inspect telemetry snapshots, flight-recorder dumps, "
                    "and bench records")
    sub = ap.add_subparsers(dest="cmd", required=True)
    mp = sub.add_parser("metrics", help="pretty-print a metrics snapshot")
    mp.add_argument("path", nargs="?", default=None,
                    help="snapshot JSON or flight dump (default: this "
                         "process's registry)")
    mp.set_defaults(fn=cmd_metrics)
    fp = sub.add_parser("flights", help="tail/inspect flight dumps")
    fp.add_argument("path", nargs="?", default=None,
                    help="one dump to inspect (default: list the dir)")
    fp.add_argument("--dir", default=None, help="flight-dump directory")
    fp.add_argument("-n", type=int, default=10, help="list at most N")
    fp.set_defaults(fn=cmd_flights)
    sp = sub.add_parser("slo", help="burn rate, brownout stage, shed "
                                    "counts, autoscaler decisions")
    sp.add_argument("path", nargs="?", default=None,
                    help="snapshot JSON or flight dump (default: this "
                         "process's registry + flight ring)")
    sp.add_argument("-n", type=int, default=20,
                    help="show at most N decision events")
    sp.set_defaults(fn=cmd_slo)
    kp = sub.add_parser("kv", help="paged-KV pool occupancy, "
                                   "fragmentation, prefix hit rate, "
                                   "per-slot page counts")
    kp.add_argument("path", nargs="?", default=None,
                    help="snapshot JSON or flight dump (default: this "
                         "process's registry)")
    kp.set_defaults(fn=cmd_kv)
    flp = sub.add_parser("fleet", help="per-replica (and per-TP-group) "
                                       "membership, breaker state, "
                                       "incarnation, heartbeat age")
    flp.add_argument("path", nargs="?", default=None,
                     help="snapshot JSON or flight dump (default: this "
                          "process's registry + flight ring)")
    flp.add_argument("-n", type=int, default=20,
                     help="show at most N membership events")
    flp.set_defaults(fn=cmd_fleet)
    lp = sub.add_parser("lint",
                        help="render a tpu-lint --json report (or run "
                             "the analyzer) as a table")
    lp.add_argument("paths", nargs="*", default=None,
                    help="a tpu-lint --json report file, or files/dirs "
                         "to analyze (default: ./paddle_tpu)")
    lp.set_defaults(fn=cmd_lint)
    bp = sub.add_parser("bench-diff",
                        help="diff two BENCH_*.json records")
    bp.add_argument("a")
    bp.add_argument("b")
    bp.add_argument("--factor", type=float, default=1.5,
                    help="flag ratios beyond this factor either way")
    bp.set_defaults(fn=cmd_bench_diff)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        raise SystemExit(main())
    except BrokenPipeError:
        # downstream pager/head closed the pipe — not a command failure
        os._exit(0)
