"""paddle_tpu.onnx — model export.

Analog of /root/reference/python/paddle/onnx/export.py, which delegates to
the external paddle2onnx package. That converter consumes the reference's
ProgramDesc format, which this framework (deliberately) does not have — the
portable deployment artifact here is the StableHLO export produced by
``paddle_tpu.jit.save`` (loadable without Python model code, versioned, and
runnable by any StableHLO consumer; see jit/serialization.py).

``export`` therefore produces that artifact and says so, rather than
pretending to emit ONNX protobufs.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None,
           export_format="onnx", **configs):
    """Export ``layer`` for deployment.

    ``export_format="onnx"`` (the default, matching the reference API)
    raises: no ONNX emitter exists in this environment (no onnx package,
    zero egress — like the reference raising when paddle2onnx is absent).
    Pass ``export_format="stablehlo"`` to write the TPU-native portable
    artifact pair (``<path>.pdmodel`` + ``.pdiparams``) instead, loadable
    by ``paddle_tpu.jit.load`` / ``paddle_tpu.inference.Predictor`` or any
    StableHLO consumer.
    """
    if export_format == "stablehlo":
        from ..jit.serialization import save

        save(layer, path, input_spec=input_spec)
        return path
    raise RuntimeError(
        "paddle_tpu.onnx.export cannot emit ONNX protobufs: no ONNX "
        "emitter/converter is available in this environment (the reference "
        "delegates to the external paddle2onnx package, which consumes a "
        "program format this framework does not have). Use "
        "export_format='stablehlo' for the portable deployment artifact, "
        "or paddle_tpu.jit.save directly.")
