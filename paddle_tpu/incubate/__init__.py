"""paddle_tpu.incubate — incubating APIs (reference python/paddle/incubate/)."""
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401

# ---- namespace parity tail (reference python/paddle/incubate/__init__.py)

from .. import inference  # noqa: F401  (reference re-exports it here)
from ..geometric import (  # noqa: F401  (legacy incubate graph names)
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
)
from ..geometric import send_u_recv as _send_u_recv


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None):
    """Legacy incubate name for geometric.send_u_recv (reference
    incubate/operators/graph_send_recv.py)."""
    return _send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                        out_size=out_size)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False):
    """Multi-hop neighbor sampling (reference incubate/operators/
    graph_khop_sampler.py): one sample_neighbors pass per hop, frontier =
    previous hop's unique neighbors; edges reindexed against the union of
    visited nodes. Returns (edge_src, edge_dst, sample_index,
    reindex_nodes[, edge_eids])."""
    import numpy as np

    from ..core.tensor import Tensor
    from ..geometric import reindex_graph, sample_neighbors

    frontier = input_nodes
    all_neighbors, all_counts, seeds_per_hop = [], [], []
    for size in sample_sizes:
        nbrs, cnts = sample_neighbors(row, colptr, frontier,
                                      sample_size=size)
        all_neighbors.append(np.asarray(nbrs._value))
        all_counts.append(np.asarray(cnts._value))
        seeds_per_hop.append(np.asarray(
            frontier._value if isinstance(frontier, Tensor) else frontier))
        frontier = Tensor(np.unique(np.asarray(nbrs._value)))
    seeds = np.concatenate(seeds_per_hop)
    nbrs = np.concatenate(all_neighbors) if all_neighbors else np.zeros(0)
    cnts = np.concatenate(all_counts) if all_counts else np.zeros(0)
    src, dst, nodes = reindex_graph(Tensor(seeds), Tensor(nbrs),
                                    Tensor(cnts))
    if return_eids:
        raise NotImplementedError(
            "graph_khop_sampler(return_eids=True): track eids via "
            "geometric.sample_neighbors(eids=..., return_eids=True)")
    return src, dst, Tensor(np.asarray(nodes._value)), nodes


def identity_loss(x, reduction="none"):
    """Reference incubate.identity_loss (the IPU loss marker op): identity
    with an optional mean/sum reduction."""
    if reduction in ("mean", 0):
        return x.mean()
    if reduction in ("sum", 1):
        return x.sum()
    if reduction in ("none", 2):
        return x
    raise ValueError(f"unknown reduction {reduction!r}")


def softmax_mask_fuse(x, mask):
    """softmax(x + mask) — the reference's fused_softmax_mask kernel
    (incubate/operators/softmax_mask_fuse.py); XLA fuses the composition."""
    from ..ops import softmax

    return softmax(x + mask, axis=-1)


def softmax_mask_fuse_upper_triangle(x):
    """Causal (upper-triangle masked) softmax — the reference's
    fused_softmax_mask_upper_triangle kernel."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops import softmax

    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    s, k = v.shape[-2], v.shape[-1]
    mask = jnp.triu(jnp.full((s, k), -1e30, v.dtype), k=1)
    return softmax((Tensor._from_value(v + mask)
                    if isinstance(x, Tensor) else v + mask), axis=-1)


__all__ = ["nn", "asp", "optimizer", "LookAhead", "ModelAverage",
           "inference", "graph_khop_sampler", "graph_reindex",
           "graph_sample_neighbors", "graph_send_recv", "identity_loss",
           "segment_max", "segment_mean", "segment_min", "segment_sum",
           "softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]
