"""Cross-process request tracing + flight recorder (ISSUE 9 flagship).

A router fronts 2 replica PROCESSES over real RPC; traffic flows with a
router-minted trace id riding every submit envelope; one replica is
SIGKILLed mid-decode. The drill asserts the telemetry layer leaves
usable artifacts:

* one rid's spans STITCH across the router and replica processes under
  a consistent trace id — including across the failover resubmit (the
  survivor's spans carry the same trace the victim was serving);
* the stitched Chrome trace is one readable timeline (distinct pids,
  shared wall-clock epoch);
* the router's flight-recorder dump (triggered by the breaker trip on
  the death) NAMES the dead replica;
* ``fleet_metrics()`` merges the replica processes' store-published
  registry snapshots: fleet-wide TTFT percentiles and tokens/s are
  answerable from the router process even though it observed no local
  engine work.
"""
import json
import os
import signal
import textwrap
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core import resilience, telemetry
from paddle_tpu.core.flags import set_flags
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.models.remote import (
    RPC_MASTER_ENV,
    TRACE_DIR_ENV,
    RemoteFrontend,
)
from paddle_tpu.models.router import ServingRouter, launch_fleet


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    resilience.reset_faults()
    telemetry.reset_telemetry()
    set_flags({"FLAGS_flight_dir": str(tmp_path / "flight")})
    yield
    resilience.reset_faults()
    telemetry.reset_telemetry()
    set_flags({"FLAGS_flight_dir": ""})


_REPLICA_SCRIPT = """
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.frontend import ServingFrontend
from paddle_tpu.models.remote import replica_main
from paddle_tpu.models.serving import ContinuousBatchingEngine

CFG = LlamaConfig(vocab_size=97, hidden_size=16, intermediate_size=32,
                  num_hidden_layers=1, num_attention_heads=2,
                  max_position_embeddings=128, tie_word_embeddings=True)


def build():
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    eng = ContinuousBatchingEngine(model, max_slots=2, max_len=64,
                                   prompt_buckets=(8, 16), do_sample=True,
                                   temperature=0.9, seed=13)
    return ServingFrontend(eng, max_queue=32, segment=4,
                           breaker_threshold=50)


if __name__ == "__main__":
    raise SystemExit(replica_main(build))
"""


def _prompts(n, rng_seed=3):
    rng = np.random.RandomState(rng_seed)
    return [rng.randint(0, 97, (int(rng.randint(4, 10)),)).astype(np.int32)
            for _ in range(n)]


def _stub(rank):
    return RemoteFrontend(f"replica{rank}", timeout=60.0,
                          health_timeout=10.0, retry_attempts=2,
                          resend_after=30.0, results_wait=0.1)


def test_cross_process_trace_stitches_across_failover(tmp_path):
    trace_dir = tmp_path / "traces"
    script = tmp_path / "replica.py"
    script.write_text(textwrap.dedent(_REPLICA_SCRIPT))
    store = rpc.init_rpc("router", rank=0, world_size=3)
    endpoint = f"127.0.0.1:{store.port}"
    fleet_store = TCPStore(port=store.port)
    router = ServingRouter(store=fleet_store, lease=1.5,
                           heartbeat_interval=0.1, max_failovers=3)
    rc_box = {}
    supervisor = threading.Thread(
        target=lambda: rc_box.update(rc=launch_fleet(
            str(script), n_replicas=2, max_restarts=2,
            env={RPC_MASTER_ENV: endpoint,
                 TRACE_DIR_ENV: str(trace_dir)},
            backoff_base=0.01, poll_interval=0.05)),
        daemon=True)
    supervisor.start()
    try:
        for rank in (0, 1):
            rpc.get_worker_info(f"replica{rank}", timeout=300)
            router.add_replica(_stub(rank), replica_id=rank)
        pids = {r: int(fleet_store.get(f"fleet/pid/{r}").decode())
                for r in (0, 1)}

        # warm pass (first-traffic XLA compiles)
        warm = [router.submit(p, max_new_tokens=2)
                for p in _prompts(2, rng_seed=7)]
        wres = router.results(wait=True, timeout_s=600)
        assert all(wres[r].status == "ok" for r in warm)

        # ---- live traffic + the kill, traces captured before it
        rids = [router.submit(p, max_new_tokens=16)
                for p in _prompts(6, rng_seed=11)]
        traces = {rid: router._requests[rid].trace for rid in rids}
        assert all(traces.values())  # router minted every trace id
        victim = max((0, 1),
                     key=lambda r: len(router._replicas[r].assigned))
        survivor = 1 - victim
        stranded = sorted(set(router._replicas[victim].assigned)
                          & set(rids))
        assert stranded, "drill needs in-flight work on the victim"
        os.kill(pids[victim], signal.SIGKILL)
        res = router.results(wait=True, timeout_s=600)
        assert set(res) >= set(rids)
        assert all(res[r].status == "ok" for r in rids)
        assert router._replicas[victim].state == "dead"

        # ---- flight recorder: the breaker-trip dump names the victim
        d = telemetry.FlightRecorder.dump_dir()
        dump_files = sorted(f for f in os.listdir(d)
                            if "breaker_trip" in f)
        assert dump_files, os.listdir(d)
        named = []
        for f in dump_files:
            data = json.load(open(os.path.join(d, f)))
            named += [e for e in data["events"]
                      if e["kind"] == "replica_dead"
                      and e["replica"] == victim]
        assert named, "no dump names the dead replica"
        assert all(e.get("reason") for e in named)
        assert any(e.get("stranded") for e in named)

        # ---- fleet metrics: merged from the replicas' store-published
        # snapshots (the router process ran no local engine)
        deadline = time.monotonic() + 30
        fm = router.fleet_metrics()
        while (fm["latency"]["ttft_s"]["count"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.5)  # next heartbeat-cadence publish
            fm = router.fleet_metrics()
        assert fm["latency"]["ttft_s"]["count"] > 0
        assert fm["latency"]["ttft_s"]["p99"] >= \
            fm["latency"]["ttft_s"]["p50"] > 0.0
        assert fm["tokens_total"] > 0
        assert fm["replicas"][victim]["state"] == "dead"
        assert fm["replicas"][survivor]["state"] == "up"
        # stats() sources its summaries from the same merge
        assert router.stats()["latency"]["ttft_s"]["count"] == \
            fm["latency"]["ttft_s"]["count"]

        # ---- shut the fleet down cleanly so the survivor (and the
        # supervisor-respawned victim) export their trace files
        rpc.get_worker_info(f"replica{victim}", timeout=300)
        router.add_replica(_stub(victim), replica_id=victim)
        router.shutdown()
    finally:
        if router._replicas:
            router.shutdown()
        supervisor.join(120)
    try:
        # ---- stitch: router spans + the replica processes' exports
        router_trace = str(tmp_path / "router-trace.json")
        telemetry.export_chrome_trace(router_trace)
        replica_files = [os.path.join(trace_dir, f)
                         for f in os.listdir(trace_dir)]
        assert replica_files, "no replica process exported a trace"
        stitched = telemetry.stitch_chrome_traces(
            [router_trace] + replica_files,
            str(tmp_path / "stitched.json"))
        events = json.load(open(stitched))["traceEvents"]

        def for_trace(t):
            return [e for e in events
                    if e.get("args", {}).get("trace") == t
                    or t in (e.get("args", {}).get("traces") or ())]

        # a request stranded on the SIGKILLed victim: its trace id must
        # appear in BOTH the router process and the survivor process
        # (the victim's spans died with it — that gap is the story), and
        # the router's failover hop events narrate the move
        rid = stranded[0]
        t = traces[rid]
        evs = for_trace(t)
        pids_seen = {e["pid"] for e in evs}
        assert len(pids_seen) >= 2, (pids_seen, len(evs))
        assert os.getpid() in pids_seen
        names = {e["name"] for e in evs}
        assert "fleet.dispatch" in names       # placement hops (router)
        assert "fleet.failover" in names       # the kill-driven resubmit
        assert "serving.retire" in names       # replica-side completion
        retires = [e for e in for_trace(t)
                   if e["name"] == "serving.retire"
                   and e["args"].get("status") == "ok"]
        assert retires and all(e["pid"] != os.getpid() for e in retires)
        dispatch_hops = [e["args"]["replica"] for e in evs
                         if e["name"] == "fleet.dispatch"]
        assert victim in dispatch_hops and survivor in dispatch_hops
        # every request's trace stitches across at least 2 processes
        for rid2 in rids:
            assert len({e["pid"] for e in for_trace(traces[rid2])}) >= 2
    finally:
        rpc.shutdown()
        fleet_store.close()
    assert rc_box.get("rc") == 0
