"""Replica serving fleet: health-gated routing, bit-exact failover,
elastic membership (ISSUE 6).

The acceptance drill: with 3 replicas under fault injection, killing one
replica mid-decode loses zero accepted requests, and every rerouted
request's token stream is bit-identical to its uninterrupted
single-replica run — the per-request sampling key streams
(``key(seed, rid, token_idx)``) make a failover replay (full, or resumed
mid-stream via ``token_base``) exactly reproduce the original schedule's
tokens. Plus: hedging cancels the loser, scale_in drains and requeues,
scale_out admits after warmup, and the clean-drain engine fixes for
requests retired mid-pipeline.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience
from paddle_tpu.core.flags import set_flags
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.frontend import ServingFrontend
from paddle_tpu.models.router import ServingRouter
from paddle_tpu.models.serving import ContinuousBatchingEngine


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.reset_faults()
    resilience.reset_counters()
    yield
    resilience.reset_faults()
    resilience.reset_counters()


_CFG = LlamaConfig(vocab_size=97, hidden_size=16, intermediate_size=32,
                   num_hidden_layers=1, num_attention_heads=2,
                   max_position_embeddings=128, tie_word_embeddings=True)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(_CFG)


def _frontend(model, max_slots=2, segment=4, do_sample=True, seed=13,
              **fe_kwargs):
    eng = ContinuousBatchingEngine(model, max_slots=max_slots, max_len=64,
                                   prompt_buckets=(8, 16),
                                   do_sample=do_sample, temperature=0.9,
                                   seed=seed)
    fe_kwargs.setdefault("breaker_threshold", 50)
    return ServingFrontend(eng, max_queue=32, segment=segment, **fe_kwargs)


def _prompts(n, rng_seed=3, lo=4, hi=10):
    rng = np.random.RandomState(rng_seed)
    return [rng.randint(0, _CFG.vocab_size,
                        (int(rng.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


def _reference(model, prompts, rids, max_new):
    """Uninterrupted single-replica run with the fleet's rids."""
    fe = _frontend(model)
    for rid, p in zip(rids, prompts):
        fe.submit(p, max_new_tokens=max_new, rid=rid)
    out = fe.results(wait=True)
    fe.shutdown()
    return {rid: out[rid].tokens for rid in rids}


# ------------------------------------------------------------- dispatch


def test_load_aware_dispatch_prefers_idle_replica(model):
    router = ServingRouter()
    busy = router.add_replica(_frontend(model))
    idle = router.add_replica(_frontend(model))
    # preload the busy replica directly so its health snapshot shows load
    for p in _prompts(4, rng_seed=1):
        router._replicas[busy].frontend.submit(p, max_new_tokens=16)
    rid = router.submit(_prompts(1, rng_seed=2)[0], max_new_tokens=4)
    assert rid in router._replicas[idle].assigned
    res = router.results(wait=True, timeout_s=120)
    assert res[rid].status == "ok"
    router.shutdown()


def test_health_payload_has_router_signals(model):
    fe = _frontend(model)
    fe.submit(_prompts(1)[0], max_new_tokens=4, priority=2)
    h = fe.health()
    assert h["kv_slots"] == 2 and 0.0 <= h["kv_occupancy"] <= 1.0
    assert h["queue_by_priority"] == {2: [1, h["queued_tokens"]]}
    assert {"breaker", "breaker_failures", "inflight", "queue_depth",
            "queued_tokens", "active_slots", "free_slots"} <= set(h)
    fe.shutdown()


def test_open_breaker_gates_replica_out(model):
    router = ServingRouter()
    a = router.add_replica(_frontend(model))
    b = router.add_replica(_frontend(model))
    router._replicas[a].breaker.trip()
    rids = [router.submit(p, max_new_tokens=4) for p in _prompts(3)]
    res = router.results(wait=True, timeout_s=120)
    assert all(res[r].status == "ok" for r in rids)
    assert all(r in router.stats()["served_by_replica"] or True
               for r in rids)
    assert router._replicas[a].served == 0
    assert router._replicas[b].served == 3
    router.shutdown()


# ------------------------------------------------------ failover drills


def test_kill_replica_mid_decode_reroutes_bit_identical(model):
    """THE acceptance drill: 3 replicas, one dies mid-decode; zero
    accepted requests lost, every rerouted token stream bit-identical to
    the uninterrupted single-replica run."""
    max_new = 12
    prompts = _prompts(6)
    router = ServingRouter(max_failovers=3)
    reps = [router.add_replica(_frontend(model)) for _ in range(3)]
    rids = [router.submit(p, max_new_tokens=max_new) for p in prompts]
    want = _reference(model, prompts, rids, max_new)
    # a couple of turns so decode is genuinely in flight fleet-wide
    router.step()
    router.step()
    victim = max(reps, key=lambda r: len(router._replicas[r].assigned))
    stranded = set(router._replicas[victim].assigned)
    assert stranded, "drill needs in-flight work on the victim"
    router.fail_replica(victim, reason="drill kill")
    res = router.results(wait=True, timeout_s=120)
    assert set(res) == set(rids)          # zero requests lost
    for rid in rids:
        assert res[rid].status == "ok"
        np.testing.assert_array_equal(res[rid].tokens, want[rid])
    assert resilience.get_counter("fleet.replica_dead") == 1
    assert resilience.get_counter("fleet.failover") >= 1
    router.shutdown()


def test_engine_fault_failover_resumes_mid_stream(model):
    """A replica that retires a request ``failed`` WITH partial tokens
    (segment dispatch fault mid-decode) hands the router a resumable
    prefix: the replay submits prompt+partials with token_base=k and the
    continuation is bit-identical."""
    max_new = 12
    prompt = _prompts(1)[0]
    router = ServingRouter(max_failovers=2)
    a = router.add_replica(_frontend(model))
    b = router.add_replica(_frontend(model))
    want = _reference(model, [prompt], [0], max_new)[0]

    # break replica a's segment program after its first decode segment:
    # the request retires "failed" there with >0 partial tokens
    rep_a = router._replicas[a]
    eng = rep_a.frontend.engine
    real_segment = eng._segment_p
    calls = {"n": 0}

    def boom(*args, **kw):
        calls["n"] += 1
        if calls["n"] > 1:
            raise RuntimeError("segment fault drill")
        return real_segment(*args, **kw)

    eng._segment_p = boom
    rid = router.submit(prompt, max_new_tokens=max_new)
    assert rid in rep_a.assigned or rid in router._replicas[b].assigned
    res = router.results(wait=True, timeout_s=120)[rid]
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, want)
    if calls["n"] > 1:  # the drill actually fired on replica a
        assert resilience.get_counter("fleet.failover") == 1
        assert resilience.get_counter("serving.poison_request") >= 1
    router.shutdown()


def test_failover_budget_exhaustion_delivers_failed(model):
    """A poison request (fails deterministically everywhere) must burn
    its failover budget and deliver ``failed`` — not ricochet forever.
    With fewer replicas than budget, the every-replica-excluded guard
    ends it; with budget < fleet size, the budget counter does."""
    set_flags({"FLAGS_fault_injection": "serving.engine_fault:*"})
    router = ServingRouter(max_failovers=2)
    for _ in range(2):
        router.add_replica(_frontend(model))
    rid = router.submit(_prompts(1)[0], max_new_tokens=6)
    res = router.results(wait=True, timeout_s=120)[rid]
    assert res.status == "failed"
    assert "exclu" in (res.reason or "") or "budget" in (res.reason or "")
    assert resilience.get_counter("fleet.failover") >= 1
    router.shutdown()

    resilience.reset_faults()
    set_flags({"FLAGS_fault_injection": "serving.engine_fault:*"})
    router2 = ServingRouter(max_failovers=1)
    for _ in range(3):
        router2.add_replica(_frontend(model))
    rid2 = router2.submit(_prompts(1)[0], max_new_tokens=6)
    res2 = router2.results(wait=True, timeout_s=120)[rid2]
    assert res2.status == "failed"
    assert resilience.get_counter("fleet.failover_budget_exhausted") == 1
    router2.shutdown()


def test_peer_failure_detector_marks_silent_replica_dead(model):
    """Store-backed liveness: a replica whose heartbeat stops is routed
    around within one lease, and its stranded work replays elsewhere."""
    store = TCPStore(is_master=True)
    try:
        lease = 0.3
        router = ServingRouter(store=store, lease=lease,
                               heartbeat_interval=0.05, max_failovers=3)
        a = router.add_replica(_frontend(model))
        b = router.add_replica(_frontend(model))
        prompts = _prompts(4)
        rids = [router.submit(p, max_new_tokens=10) for p in prompts]
        want = _reference(model, prompts, rids, 10)
        router.step()
        # silence replica a: its beat thread stops but its frontend is
        # never told — only the lease can reveal the death
        rep_a = router._replicas[a]
        rep_a.hb.stop(1.0)
        rep_a.hb = None
        time.sleep(lease + 0.15)
        deadline = time.monotonic() + 10
        while (rep_a.state == "up" and time.monotonic() < deadline):
            router.step()
        assert rep_a.state == "dead"
        res = router.results(wait=True, timeout_s=120)
        for rid in rids:
            assert res[rid].status == "ok"
            np.testing.assert_array_equal(res[rid].tokens, want[rid])
        assert router._replicas[b].state == "up"
        router.shutdown()
    finally:
        store.close()


def test_elastic_peer_dead_site_drills_detector_path(model):
    """The ``elastic.peer_dead`` fault site fires through the active
    detector machinery; the router's sweep path is exercised by a
    detector-armed store fleet in the test above — here the site proves
    the shared injection plumbing reaches gang.check_peers()."""
    from paddle_tpu.distributed.gang import PeerFailureError, check_peers

    set_flags({"FLAGS_fault_injection": "elastic.peer_dead:1"})
    with pytest.raises(PeerFailureError):
        check_peers("fleet drill")
    assert resilience.get_counter("gang.peer_dead") == 1


# --------------------------------------------------------------- hedging


def test_hedging_first_result_wins_and_loser_cancelled(model):
    router = ServingRouter()
    a = router.add_replica(_frontend(model))
    b = router.add_replica(_frontend(model))
    prompt = _prompts(1)[0]
    want = _reference(model, [prompt], [0], 8)[0]
    rid = router.submit(prompt, max_new_tokens=8, hedge=True)
    # both replicas carry the request
    assert rid in router._replicas[a].assigned
    assert rid in router._replicas[b].assigned
    assert resilience.get_counter("fleet.hedged") == 1
    res = router.results(wait=True, timeout_s=120)
    assert list(res) == [rid] and res[rid].status == "ok"
    np.testing.assert_array_equal(res[rid].tokens, want)
    # the loser was cancelled, not left decoding
    assert rid not in router._replicas[a].assigned
    assert rid not in router._replicas[b].assigned
    # exactly one replica SERVED it; the loser's cancel is internal and
    # never surfaces as a second client result
    assert router._replicas[a].served + router._replicas[b].served == 1
    router.shutdown()


def test_hedged_request_survives_one_arm_failing(model):
    prompt = _prompts(1)[0]
    # reference FIRST: it must not consume the injection budget below
    want = _reference(model, [prompt], [0], 8)[0]
    set_flags({"FLAGS_fault_injection": "serving.engine_fault:1"})
    router = ServingRouter()
    router.add_replica(_frontend(model))
    router.add_replica(_frontend(model))
    rid = router.submit(prompt, max_new_tokens=8, hedge=True)
    res = router.results(wait=True, timeout_s=120)[rid]
    assert res.status == "ok"
    np.testing.assert_array_equal(res.tokens, want)
    router.shutdown()


def test_scale_in_with_hedged_request_drops_arm_not_resubmits(model):
    """Draining a replica holding one hedge arm must DROP that arm (the
    other copy is the requeue), never resubmit the rid onto the replica
    already running it."""
    router = ServingRouter()
    a = router.add_replica(_frontend(model))
    b = router.add_replica(_frontend(model))
    prompt = _prompts(1)[0]
    want = _reference(model, [prompt], [0], 8)[0]
    rid = router.submit(prompt, max_new_tokens=8, hedge=True)
    assert rid in router._replicas[a].assigned
    assert rid in router._replicas[b].assigned
    victim = a  # both hold a copy; drain one before any decode
    router.scale_in(victim)  # must not raise "rid already pending"
    res = router.results(wait=True, timeout_s=120)
    assert list(res) == [rid] and res[rid].status == "ok"
    np.testing.assert_array_equal(res[rid].tokens, want)
    router.shutdown()


def test_router_cancel_preserves_inflight_partial_tokens(model):
    """router.cancel() keeps the partial tokens an in-flight copy
    already produced — same contract as ServingFrontend.cancel."""
    router = ServingRouter()
    router.add_replica(_frontend(model))
    prompt = _prompts(1)[0]
    want = _reference(model, [prompt], [0], 16)[0]
    rid = router.submit(prompt, max_new_tokens=16)
    router.step()
    router.step()  # a few decode segments emitted
    assert router.cancel(rid)
    res = router.results()[rid]
    assert res.status == "cancelled"
    assert 0 < res.tokens.size < 16
    np.testing.assert_array_equal(res.tokens, want[:res.tokens.size])
    router.shutdown()


# ------------------------------------------------------------ elasticity


def test_scale_in_drains_in_flight_and_requeues_queued(model):
    router = ServingRouter()
    a = router.add_replica(_frontend(model, max_slots=2))
    b = router.add_replica(_frontend(model, max_slots=2))
    prompts = _prompts(8)
    rids = [router.submit(p, max_new_tokens=8) for p in prompts]
    want = _reference(model, prompts, rids, 8)
    router.step()  # some requests decoding, some still queued on replicas
    router.scale_in(a)
    assert a not in router._replicas
    assert resilience.get_counter("fleet.scale_in") == 1
    res = router.results(wait=True, timeout_s=120)
    for rid in rids:
        assert res[rid].status == "ok", res[rid]
        np.testing.assert_array_equal(res[rid].tokens, want[rid])
    router.shutdown()


def test_scale_out_admits_warmed_replica_and_takes_load(model):
    router = ServingRouter()
    router.add_replica(_frontend(model, max_slots=1))
    prompts = _prompts(8)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts[:5]]
    warmed = {}
    fe = _frontend(model, max_slots=1)
    real_warm = fe.warmup
    fe.warmup = lambda **kw: warmed.setdefault("done", True) or real_warm()
    new_id = router.scale_out(fe)
    assert warmed.get("done") is True  # admitted AFTER warmup ran
    assert resilience.get_counter("fleet.scale_out") == 1
    # traffic arriving after the scale-out lands on the idle new replica
    rids += [router.submit(p, max_new_tokens=6) for p in prompts[5:]]
    assert any(r in router._replicas[new_id].assigned for r in rids)
    res = router.results(wait=True, timeout_s=120)
    assert all(res[r].status == "ok" for r in rids)
    assert router._replicas[new_id].served > 0  # the new replica worked
    router.shutdown()


def test_no_live_replica_delivers_unavailable(model):
    router = ServingRouter()
    a = router.add_replica(_frontend(model))
    rid = router.submit(_prompts(1)[0], max_new_tokens=6)
    router.fail_replica(a)
    res = router.results(wait=True, timeout_s=10)[rid]
    assert res.status == "unavailable"
    router.shutdown()


def test_fleet_under_launch_supervisor_worker_restart_policy(tmp_path):
    """The fleet's failure domain under launch(): restart_policy="worker"
    respawns ONLY the crashed replica (survivors keep their pids) within
    the restart budget."""
    import textwrap

    from paddle_tpu.models.router import launch_fleet

    script = tmp_path / "replica.py"
    script.write_text(textwrap.dedent("""
        import os, pathlib, sys, time
        rank = os.environ["PADDLE_TRAINER_ID"]
        gen = os.environ["PADDLE_ELASTIC_GENERATION"]
        out = pathlib.Path(os.environ["FLEET_OUT"]) / f"rank{rank}.gen{gen}"
        out.write_text(str(os.getpid()))
        if rank == "1" and gen == "0":
            sys.exit(1)          # replica 1 crashes in generation 0
        # survivors / respawned replica serve briefly then exit clean
        time.sleep(0.4)
        sys.exit(0)
    """))
    rc = launch_fleet(str(script), n_replicas=2, max_restarts=2,
                      env={"FLEET_OUT": str(tmp_path)},
                      backoff_base=0.01, poll_interval=0.05)
    assert rc == 0
    assert resilience.get_counter("gang.replica_restart") == 1
    # replica 1 ran twice (gen 0 crash + gen 1 respawn); replica 0 once
    assert (tmp_path / "rank1.gen0").exists()
    assert (tmp_path / "rank1.gen1").exists()
    assert (tmp_path / "rank0.gen0").exists()
    assert not (tmp_path / "rank0.gen1").exists()  # survivor untouched


# ------------------------------------- engine clean-drain (mid-pipeline)


def test_abort_mid_pipeline_leaves_no_stale_carry(model):
    eng = ContinuousBatchingEngine(model, max_slots=2, max_len=64,
                                   prompt_buckets=(8, 16), pipeline=True)
    eng.start(segment=4)
    p = _prompts(2, rng_seed=9)
    r0 = eng.submit(p[0], 16)
    r1 = eng.submit(p[1], 16)
    eng.step()
    eng.step()  # pipeline now holds an in-flight speculative segment
    assert eng._inflight is not None
    eng.abort(r0.rid, "cancelled")
    eng.abort(r1.rid, "cancelled")
    # the carry still counts as work: the next step must drain it
    assert eng.has_work()
    while eng.has_work():
        eng.step()
    assert eng._inflight is None
    st = eng.stats()
    assert st["cancelled"] == 2 and st["failed"] == 0
    # freed slots are back at the idle length, not the stale device view
    assert list(eng._lengths) == [1, 1]
    # and the engine is immediately reusable with exact tokens
    outs, st2 = eng.run(p, max_new_tokens=6, segment=4)
    eng2 = ContinuousBatchingEngine(model, max_slots=2, max_len=64,
                                    prompt_buckets=(8, 16), pipeline=True)
    outs2, _ = eng2.run(p, max_new_tokens=6, segment=4)
    for a, b in zip(outs, outs2):
        np.testing.assert_array_equal(a, b)


def test_frontend_cancel_all_mid_pipeline_drains_and_counts(model):
    fe = _frontend(model, do_sample=False)
    rids = [fe.submit(p, max_new_tokens=16) for p in _prompts(2)]
    fe.step()
    fe.step()
    for rid in rids:
        fe.cancel(rid)
    res = fe.results(wait=True)
    assert {res[r].status for r in rids} == {"cancelled"}
    assert fe.engine._inflight is None
    assert not fe.engine.has_work()
    st = fe.engine.stats()
    assert st["cancelled"] == 2 and st["failed"] == 0
    fe.shutdown()


def test_token_base_resume_is_bit_identical(model):
    """Engine-level contract behind router failover: submitting
    prompt+emitted with token_base=k continues the stream exactly."""
    max_new = 12
    prompt = _prompts(1)[0]
    fe = _frontend(model)
    fe.submit(prompt, max_new_tokens=max_new, rid=7)
    want = fe.results(wait=True)[7].tokens
    fe.shutdown()
    for k in (1, 5, max_new - 1):
        fe2 = _frontend(model)
        fe2.submit(np.concatenate([prompt, want[:k]]),
                   max_new_tokens=max_new - k, rid=7, token_base=k)
        cont = fe2.results(wait=True)[7].tokens
        np.testing.assert_array_equal(cont, want[k:])
        fe2.shutdown()


def test_router_overhead_stat_is_small(model):
    router = ServingRouter()
    for _ in range(2):
        router.add_replica(_frontend(model))
    rids = [router.submit(p, max_new_tokens=8) for p in _prompts(6)]
    res = router.results(wait=True, timeout_s=120)
    assert all(res[r].status == "ok" for r in rids)
    st = router.stats()
    assert st["router_overhead_pct"] < 5.0, st
    assert st["pump_s"] > 0
    router.shutdown()
