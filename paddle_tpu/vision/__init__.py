"""paddle_tpu.vision — transforms, datasets, model zoo.

Analog of /root/reference/python/paddle/vision/.
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms",
           "get_image_backend", "set_image_backend", "image_load"]

_image_backend = "pil"


def get_image_backend():
    """Reference vision.image.get_image_backend."""
    return _image_backend


def set_image_backend(backend):
    """Reference set_image_backend: 'pil' or 'cv2' (cv2 is not shipped;
    selecting it raises like the reference does for missing backends)."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"expected 'pil' or 'cv2', got {backend!r}")
    if backend == "cv2":
        try:
            import cv2  # noqa: F401
        except ImportError as e:
            raise ValueError("cv2 backend requested but OpenCV is not "
                             "installed in this build") from e
    _image_backend = backend


def image_load(path, backend=None):
    """Reference vision.image_load: returns a PIL.Image (pil backend)."""
    backend = backend or _image_backend
    if backend == "pil":
        from PIL import Image

        return Image.open(path)
    import cv2

    return cv2.imread(path)
