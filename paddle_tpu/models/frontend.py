"""Serving frontend: admission control, load shedding, circuit breaking,
graceful drain — the request-lifecycle layer over ContinuousBatchingEngine.

The engine (serving.py) is a pure scheduler: it decodes whatever sits in
its queue. Production traffic needs the layer above it — the part of a
vLLM-style serving stack that decides what is ALLOWED to reach the
scheduler and how the system degrades when it is saturated or broken:

* **Bounded admission queue** — ``submit()`` sheds load instead of
  buffering unboundedly: past ``max_queue`` entries or a
  ``max_queued_tokens`` backlog the request is ``"rejected"`` at the
  door. With priority classes, a higher-priority admission evicts the
  lowest-priority queued request (high-priority work sheds LAST).
* **Circuit breaker** — repeated engine-level failures (poison requests
  retired as ``"failed"``) trip a ``core.resilience.CircuitBreaker``;
  while it is open every submit fails fast as ``"unavailable"`` instead
  of feeding a broken engine, and a half-open probe request closes it
  again on success.
* **Graceful drain** — ``shutdown(drain=True)`` stops admitting,
  finishes the slots already decoding, and reports ``"cancelled"`` for
  everything still queued; ``drain=False`` cancels in-flight work too.
* **Health** — ``health()`` / ``ready()`` snapshots for watchdogs
  (``fleet.elastic.CommTaskManager`` can both scope ``step()`` under its
  timeout watch and poll ``ready`` as a registered probe).

The frontend is a synchronous pump: callers ``submit()`` whenever
requests arrive and drive progress with ``step()`` (one admit → decode →
retire turn) or ``results(wait=True)`` (pump until everything pending has
resolved). With the engine's overlapped scheduler
(``FLAGS_serving_pipeline``, default on) each pumped turn dispatches the
NEXT decode segment before consuming the previous one, so results arrive
one segment behind the device — admission control, poison bisection,
deadlines, and the circuit breaker are unchanged because the engine
drains its pipeline before any admission, bisection replay, or
mask-changing retirement. ``warmup()`` (delegated to the engine)
AOT-compiles every serving shape so the first request pays no compile
time. Request statuses:
``ok | timed_out | rejected | failed | cancelled | unavailable``.
"""
from __future__ import annotations

import bisect
import itertools
import time

import numpy as np

from ..core import perfwatch, telemetry
from ..core.resilience import CircuitBreaker, Deadline, bump_counter
from .qos import FairClock, QoSPolicy, tenant_label
from .serving import TERMINAL_STATES as _ENGINE_TERMINAL

__all__ = ["ServingFrontend", "RequestResult", "TERMINAL_STATES",
           "latency_summaries"]

# Every terminal status a frontend result can carry: the engine's set
# plus the admission-level verdicts minted here. The fleet router's
# retirement switch is CI-gated against this set.
TERMINAL_STATES = frozenset(_ENGINE_TERMINAL | {"rejected", "unavailable"})

# admission-layer metrics (module-level handles — see serving.py note).
# serving.requests_total is shared with the engine: the engine stamps
# the terminal states of requests it admitted; the frontend stamps the
# verdicts the engine never saw (admission rejected/unavailable, queue
# expiry timed_out, queue cancels) — so the one labeled counter covers
# the whole status space.
_M_QWAIT = telemetry.histogram(
    "serving.queue_wait_s", "frontend admission-queue wait, submit -> "
    "engine admission")
_M_REQS = telemetry.counter("serving.requests_total")
_M_SLO_SHED = telemetry.counter(
    "serving.slo_shed", "admissions shed by the SLO burn-rate monitor "
    "(FLAGS_slo_shedding on, alarm up, priority below the protected "
    "class)")
# the admission-verdict counters also carry {tenant, priority}
# attribution series (label-less series = historical totals; labeled
# series answer WHOSE traffic was turned away during an incident)
_M_REJECTED = telemetry.counter("serving.rejected")
_M_SHED = telemetry.counter("serving.shed")
_M_QUOTA = telemetry.counter(
    "serving.quota_rejected", "admissions rejected because the tenant's "
    "outstanding token cost would exceed its QoS quota_tokens")

# the latency histograms every health/stats summary reads, keyed by the
# short name the payloads use
_LATENCY_HISTS = {"ttft_s": "serving.ttft_s",
                  "token_s": "serving.token_latency_s",
                  "queue_wait_s": "serving.queue_wait_s"}


def latency_summaries(snapshot=None) -> dict:
    """p50/p95/p99 + count/mean (seconds) for the serving latency
    histograms — from the process registry by default, or from a
    (possibly fleet-merged) ``MetricsRegistry.snapshot()`` dict. Shared
    by ``ServingFrontend.health()``, ``ServingRouter.stats()`` and
    ``ServingRouter.fleet_metrics()``."""
    out = {}
    for key, name in _LATENCY_HISTS.items():
        if snapshot is not None:
            out[key] = telemetry.summary_from_snapshot(snapshot, name)
        else:
            out[key] = telemetry.histogram(name).summary()
    return out


class RequestResult:
    """Terminal record for one submitted request. ``token_base`` is the
    sampling-stream offset the attempt was submitted with (the failover
    resume contract): ``tokens`` covers stream indices ``[token_base,
    token_base + len(tokens))``, so a fleet router recombines a resumed
    attempt as ``known_prefix[:token_base] + tokens`` instead of
    trusting that its emitted bookkeeping exactly matches the attempt."""

    __slots__ = ("rid", "status", "tokens", "reason", "token_base")

    def __init__(self, rid, status, tokens=None, reason=None,
                 token_base=0):
        self.rid = rid
        self.status = status
        self.tokens = (np.zeros((0,), np.int32) if tokens is None
                       else np.asarray(tokens, np.int32))
        self.reason = reason
        self.token_base = int(token_base)

    def __repr__(self):
        return (f"RequestResult(rid={self.rid}, status={self.status!r}, "
                f"tokens={len(self.tokens)})")


class _Pending:
    """A queued admission, ordered by (priority DESC, WFQ virtual
    finish tag ASC, arrival ASC). ``vft`` is the start-time-fair-queue
    tag (``qos.FairClock``): within one priority class tenants
    interleave by weighted share instead of raw arrival order — for a
    single tenant the tags are arrival-monotonic, so the historical
    FIFO-within-priority order is preserved bit-for-bit."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "priority", "deadline",
                 "cost", "seq", "token_base", "trace", "tenant", "vft",
                 "t0m", "t0w", "hold_kv", "kv_import")

    def __init__(self, rid, prompt, max_new_tokens, priority, deadline,
                 seq, token_base=0, trace=None, tenant=None, vft=0.0,
                 hold_kv=False, kv_import=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.priority = priority
        self.deadline = deadline
        # backlog cost: prompt tokens to prefill + tokens to decode
        self.cost = prompt.size + max_new_tokens
        self.seq = seq
        self.token_base = token_base
        self.trace = trace              # telemetry trace id
        self.tenant = tenant
        self.vft = float(vft)           # WFQ virtual finish tag
        self.t0m = time.monotonic()     # queue-wait anchor
        self.t0w = time.time()  # wall-clock: x-process trace epoch
        self.hold_kv = bool(hold_kv)    # disaggregated prefill leg
        self.kv_import = kv_import      # adopt this completed KV import

    def __lt__(self, other):
        return ((-self.priority, self.vft, self.seq)
                < (-other.priority, other.vft, other.seq))


class ServingFrontend:
    """submit()/results()/cancel() lifecycle over a
    ``ContinuousBatchingEngine`` (requests arrive over time, not as one
    list), with bounded admission, failure isolation surfaced as request
    statuses, a circuit breaker, and graceful drain.

    Usage::

        fe = ServingFrontend(engine, max_queue=32, max_queued_tokens=4096)
        rid = fe.submit(prompt, max_new_tokens=64, priority=1)
        for rid, res in fe.results(wait=True).items():
            print(rid, res.status, res.tokens)
        fe.shutdown(drain=True)
    """

    # the router treats local frontends and RemoteFrontend stubs
    # (models/remote.py) interchangeably; this flag picks the handling
    # that differs (who heartbeats, who pumps)
    is_remote = False

    def __init__(self, engine, max_queue=64, max_queued_tokens=None,
                 default_max_new_tokens=64, segment=16, breaker=None,
                 breaker_threshold=5, breaker_cooldown_s=30.0,
                 watchdog=None, watch_name="serving.step", slo=None,
                 qos=None, brownout=None, role="both"):
        self.engine = engine
        # disaggregation role this replica declares to the fleet router:
        # "prefill" (prompt leg only), "decode" (adopts transferred KV),
        # or "both" (colocated — the default, and the pre-disagg
        # behavior). Advisory: the ENGINE serves whatever arrives; the
        # router's candidate filter is what enforces pool membership,
        # so a role mismatch degrades to colocated serving, never loss.
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"role must be prefill|decode|both, "
                             f"got {role!r}")
        self.role = role
        # SLO monitor (perfwatch): declared TTFT / per-token objectives
        # evaluated over the process registry histograms. Always present
        # (status() is cheap and gated); shedding only ever engages
        # behind FLAGS_slo_shedding.
        self.slo = slo if slo is not None else perfwatch.SLOMonitor()
        # multi-tenant QoS: tenant weights feed the WFQ admission order,
        # quota_tokens bounds each tenant's outstanding cost. The
        # default policy has no quotas and uniform weights — tenant-less
        # traffic behaves exactly as before.
        self.qos = qos if qos is not None else QoSPolicy()
        self._fair = FairClock(self.qos)
        self._tenant_out: dict = {}   # tenant -> outstanding token cost
        self._req_cost: dict = {}     # rid -> (tenant, cost)
        # brownout ladder (perfwatch): staged degradation under a
        # sustained burn alarm. Inert unless FLAGS_brownout (or an
        # explicitly enabled controller) — same opt-in discipline as
        # FLAGS_slo_shedding.
        self.brownout = (brownout if brownout is not None
                         else perfwatch.BrownoutController(self.slo,
                                                           qos=self.qos))
        self.max_queue = int(max_queue)
        self.max_queued_tokens = max_queued_tokens
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.breaker = breaker or CircuitBreaker(
            "serving.engine", failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s)
        self._watchdog = watchdog
        self._watch_name = watch_name
        self._queue: list[_Pending] = []   # sorted: high priority first
        self._inflight = {}                # rid -> engine Request
        self._probe_rids = set()           # half-open probes awaiting verdict
        self._results: dict[int, RequestResult] = {}
        self._rids = itertools.count()
        self._seq = itertools.count()
        self._draining = False
        self._closed = False
        self._segment = int(segment)
        engine.start(segment=segment)

    def warmup(self, cache_dir=None):
        """AOT-compile every engine shape at THIS frontend's segment
        length (see ``ContinuousBatchingEngine.warmup``) so the first
        submitted request hits only precompiled programs."""
        return self.engine.warmup(segment=self._segment,
                                  cache_dir=cache_dir)

    def fingerprint(self) -> tuple:
        """The engine identity a fleet router checks at registration:
        replicas serving the same weights with the same seed/sampling
        config produce bit-identical streams, which is the failover
        contract. Plain numbers so it crosses the RPC wire."""
        eng = self.engine
        return (eng._seed, eng.do_sample, eng.temperature, eng.top_k,
                eng.top_p, eng.eos_token_id)

    # ------------------------------------------------------------ admission

    def _finish(self, rid, status, tokens=None, reason=None,
                token_base=0):
        self._results[rid] = RequestResult(rid, status, tokens, reason,
                                           token_base=token_base)
        # quota accounting: a terminal verdict releases the tenant's
        # outstanding token cost (single release point — every path,
        # admission reject included, lands here)
        held = self._req_cost.pop(rid, None)
        if held is not None:
            tenant, cost = held
            left = self._tenant_out.get(tenant, 0) - cost
            if left > 0:
                self._tenant_out[tenant] = left
            else:
                self._tenant_out.pop(tenant, None)
        return rid

    def _reject(self, rid, reason, tenant=None, priority=0):
        bump_counter("serving.rejected")
        if telemetry.enabled():
            _M_REQS.inc(status="rejected")  # engine never saw it
            _M_REJECTED.inc(tenant=tenant_label(tenant),
                            priority=int(priority))
        self.engine.note_rejection()  # stats()['rejected'] sees shedding
        return self._finish(rid, "rejected", reason=reason)

    def _cancel_bookkeeping(self, rid, tokens=None, reason="",
                            token_base=0):
        self._inflight.pop(rid, None)
        bump_counter("serving.cancelled")
        self._finish(rid, "cancelled", tokens=tokens, reason=reason,
                     token_base=token_base)
        self._resolve_probe(rid, "cancelled")

    def queued_tokens(self) -> int:
        return sum(e.cost for e in self._queue)

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_s=None, rid=None, token_base=0,
               trace=None, tenant=None, hold_kv=False,
               kv_import=None) -> int:
        """Admit one request; returns its rid. Never raises for a bad or
        shed request — the verdict lands in ``results()`` as status
        ``rejected`` (admission control / malformed / tenant over
        quota), ``unavailable`` (circuit open), or a terminal decode
        status later. ``tenant`` selects the QoS lane: the tenant's WFQ
        weight orders it within its priority class, its ``quota_tokens``
        bounds the outstanding cost it may hold here, and its metrics
        series attribute the latency it sees.

        ``rid`` lets a caller that owns the request-id space (the fleet
        ``ServingRouter`` — sampling streams are keyed on the rid, so a
        failover replay must reuse it) name the request; a rid already
        pending here raises ``ValueError``. ``token_base`` is the
        engine's failover-resume contract (see
        ``ContinuousBatchingEngine.submit``). ``trace`` is the telemetry
        trace id the request's spans stitch under — a standalone
        frontend MINTS one here; a fleet router passes its own (minted
        at ``ServingRouter.submit``, riding the RPC envelope)."""
        if trace is None and telemetry.enabled():
            trace = telemetry.new_trace_id()
        if rid is None:
            rid = next(self._rids)
        else:
            if rid in self._inflight or any(e.rid == rid
                                            for e in self._queue):
                raise ValueError(f"rid {rid} is already pending on this "
                                 "frontend")
            if isinstance(rid, int) and rid >= 0:
                # keep auto rids strictly above explicit ones (no aliasing)
                self._rids = itertools.count(
                    max(rid + 1, next(self._rids)))
        if self._closed or self._draining:
            return self._reject(rid, "shutting down", tenant, priority)
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if telemetry.enabled():
            # brownout ladder (FLAGS_brownout): staged degradation —
            # cap budgets, then shed low priority, then over-share
            # tenants, then everything below the protected class. Inert
            # at stage 0 / flag off. over_share is a thunk: the
            # fair-share scan only runs at stage >= 3, not per submit.
            act, max_new, why = self.brownout.admit(
                tenant, priority, max_new,
                over_share=lambda: self.qos.over_share(tenant,
                                                       self._tenant_out))
            if act == "shed":
                return self._reject(rid, why, tenant, priority)
            if self.slo.should_shed(priority):
                # legacy binary burn-rate shedding (FLAGS_slo_shedding):
                # while the SLO error budget burns past threshold,
                # low-priority admissions are turned away at the door so
                # the protected classes keep their latency
                _M_SLO_SHED.inc()
                _M_SLO_SHED.inc(tenant=tenant_label(tenant),
                                priority=int(priority))
                return self._reject(
                    rid, "slo burn-rate shed (error budget burning; "
                         f"priority {int(priority)} below protected "
                         "class)", tenant, priority)
        try:
            prompt = np.asarray(prompt).astype(np.int32).ravel()
            self.engine._validate(prompt, max_new)
        except (ValueError, TypeError) as e:
            # a request the engine could NEVER schedule is a poison pill
            # caught at the door — admission is where it must die, not
            # inside a co-batched dispatch
            return self._reject(rid, str(e), tenant, priority)
        # tenant token-budget quota: outstanding cost (queued + admitted,
        # prompt tokens + decode budget) may not exceed quota_tokens.
        # The frontend's submit never raises — the typed
        # TenantQuotaExceeded surface is the ROUTER's client API; here
        # the verdict is a "rejected" result with the same accounting.
        cost = int(prompt.size) + int(max_new)
        if not self.qos.check_quota(tenant,
                                    self._tenant_out.get(tenant, 0), cost):
            bump_counter("serving.quota_rejected")
            if telemetry.enabled():
                _M_QUOTA.inc(tenant=tenant_label(tenant))
            return self._reject(
                rid, f"tenant {tenant_label(tenant)} over quota "
                     f"({self._tenant_out.get(tenant, 0)} outstanding + "
                     f"{cost} > {self.qos.quota_tokens(tenant)} tokens)",
                tenant, priority)
        probe = False
        if self.breaker.state() != CircuitBreaker.CLOSED:
            # half-open admission goes through the breaker's own probe
            # accounting (allow() consumes one of half_open_max slots);
            # while open, allow() is False and we fail fast
            if not self.breaker.allow():
                bump_counter("serving.unavailable")
                if telemetry.enabled():
                    _M_REQS.inc(status="unavailable")
                return self._finish(
                    rid, "unavailable",
                    reason=f"circuit breaker {self.breaker.state()}")
            probe = True
        entry = _Pending(rid, prompt, max_new, int(priority),
                         (deadline_s if isinstance(deadline_s, Deadline)
                          else Deadline(deadline_s)), next(self._seq),
                         token_base=int(token_base), trace=trace,
                         tenant=tenant, hold_kv=hold_kv,
                         kv_import=kv_import)
        if telemetry.enabled():
            telemetry.trace_event("serving.submit", trace=trace, rid=rid,
                                  prompt_tokens=int(prompt.size),
                                  max_new=max_new, priority=int(priority))
        self._sweep_expired()  # dead entries must not shed live traffic
        # bounded admission: shed the lowest-priority queued request
        # (LAST in sorted order) while budgets are exceeded — but only
        # after proving the newcomer CAN fit once every out-ranked entry
        # is gone; an infeasible request must not empty the queue first
        if self._over_budget(entry) and not self._feasible(entry):
            if probe:
                self.breaker.release_probe()
            return self._reject(
                rid, f"admission queue full "
                     f"(depth {len(self._queue)}/{self.max_queue})",
                tenant, priority)
        while self._over_budget(entry):
            # _feasible guarantees the tail outranks nothing: every
            # remaining over-budget token/slot is held by a lower-priority
            # entry, so the victim is always evictable
            victim = self._queue.pop()
            bump_counter("serving.shed")
            if telemetry.enabled():
                _M_SHED.inc(tenant=tenant_label(victim.tenant),
                            priority=int(victim.priority))
            self._reject(victim.rid, "shed by higher-priority admission",
                         victim.tenant, victim.priority)
            self._resolve_probe(victim.rid, "rejected")
        # the WFQ tag is charged to the tenant's lane only once the
        # entry is ACCEPTED: a queue-full rejection must not push the
        # tenant's virtual start time into the future, or a burst of
        # rejections would deprioritize its post-overload traffic
        entry.vft = self._fair.tag(entry.priority, tenant, entry.cost)
        # quota accounting: the entry now holds its cost until terminal
        self._req_cost[rid] = (tenant, entry.cost)
        self._tenant_out[tenant] = (self._tenant_out.get(tenant, 0)
                                    + entry.cost)
        bisect.insort(self._queue, entry)
        if probe:
            self._probe_rids.add(rid)
        return rid

    def _over_budget(self, entry) -> bool:
        if len(self._queue) + 1 > self.max_queue:
            return True
        if self.max_queued_tokens is not None:
            return self.queued_tokens() + entry.cost > self.max_queued_tokens
        return False

    def _feasible(self, entry) -> bool:
        """Could ``entry`` fit the budgets after evicting every queued
        request it outranks? (Entries of equal/higher priority are never
        evicted on its behalf.)"""
        kept = [e for e in self._queue if e.priority >= entry.priority]
        if len(kept) + 1 > self.max_queue:
            return False
        if self.max_queued_tokens is not None:
            return (sum(e.cost for e in kept) + entry.cost
                    <= self.max_queued_tokens)
        return True

    # ------------------------------------------------------------- pumping

    def _watched(self, fn):
        """Run ``fn`` under the watchdog's watch scope (when given) so a
        wedged engine dispatch trips the ``CommTaskManager`` timeout
        dump."""
        if self._watchdog is not None:
            from ..distributed.fleet.elastic import watch

            with watch(self._watchdog, self._watch_name):
                return fn()
        return fn()

    def step(self):
        """One scheduler turn: move admissible queued requests into the
        engine's free slots, run one decode segment, record outcomes —
        watchdog-scoped."""
        return self._watched(self._step)

    def _sweep_expired(self):
        """Retire queue entries whose deadline ran out, independent of
        free slots: while the engine is saturated they would otherwise
        keep pinning the queue/backlog budgets and shed live traffic for
        dead work. Runs on every step AND every admission attempt."""
        live = []
        for entry in self._queue:
            if entry.deadline.expired():
                if telemetry.enabled():
                    _M_REQS.inc(status="timed_out")  # engine never saw it
                self._finish(entry.rid, "timed_out",
                             reason="expired while queued",
                             token_base=entry.token_base)
                self._resolve_probe(entry.rid, "timed_out")
            else:
                live.append(entry)
        self._queue = live

    def _step(self):
        if telemetry.enabled():
            # keep the burn-rate windows current even when nobody polls
            # health(); rate-limited inside the monitor — and let the
            # brownout ladder step with the alarm (inert unless enabled)
            self.slo.status()
            self.brownout.maybe_step()
        self._sweep_expired()
        room = self.engine.free_slots() - len(self.engine.queued_requests())
        if getattr(self.engine, "admission_blocked", False):
            # the engine's KV page pool deferred its queue head last
            # step: hold admissions HERE, in the priority/WFQ queue,
            # instead of spilling them into the engine's FIFO where
            # priority ordering no longer applies
            room = 0
        while room > 0 and self._queue:
            entry = self._queue.pop(0)
            # WFQ: dispatching advances the class's virtual clock so
            # late-arriving tenants start at the present
            self._fair.advance(entry.priority, entry.vft)
            req = self.engine.submit(entry.prompt, entry.max_new_tokens,
                                     deadline_s=entry.deadline,
                                     rid=entry.rid,
                                     token_base=entry.token_base,
                                     trace=entry.trace,
                                     tenant=entry.tenant,
                                     hold_kv=entry.hold_kv,
                                     kv_import=entry.kv_import)
            # TTFT anchors at frontend SUBMIT time, not engine admission
            # — queue wait is part of the latency a client sees
            req.t_submit = entry.t0m
            if telemetry.enabled():
                wait = time.monotonic() - entry.t0m
                _M_QWAIT.observe(wait)
                if entry.tenant is not None:
                    # per-tenant series: the WFQ fairness bound ("a hot
                    # tenant cannot blow a quiet tenant's queue wait")
                    # is asserted on exactly this attribution
                    _M_QWAIT.observe(wait, tenant=str(entry.tenant))
                telemetry.tracer().add_span(
                    "serving.queue_wait", entry.t0w, wait,
                    trace=entry.trace, rid=entry.rid)
            self._inflight[entry.rid] = req
            room -= 1
        if self.engine.has_work():
            self._record(self.engine.step())

    def _record(self, finished):
        for req in finished:
            self._inflight.pop(req.rid, None)
            self._finish(req.rid, req.status, tokens=req.output(),
                         reason=(str(req.error) if req.error is not None
                                 else None), token_base=req.token_base)
            if req.status == "failed":
                # while recovering, only a PROBE's failure re-trips; a
                # stale failure from pre-trip work is not probe evidence
                if (self.breaker.state() != CircuitBreaker.HALF_OPEN
                        or req.rid in self._probe_rids):
                    self.breaker.record_failure()
            elif req.status == "ok":
                # while recovering, only an admitted PROBE's success is
                # evidence the engine healed — a stale ok from pre-trip
                # work must not close the breaker on its behalf
                if (self.breaker.state() == CircuitBreaker.CLOSED
                        or req.rid in self._probe_rids):
                    self.breaker.record_success()
            self._resolve_probe(req.rid, req.status)

    def _resolve_probe(self, rid, status):
        """A half-open probe that resolved WITHOUT a verdict on the engine
        (cancelled / its own deadline) frees its probe slot; ok/failed
        verdicts already closed or re-opened the breaker."""
        if rid in self._probe_rids:
            self._probe_rids.discard(rid)
            if status not in ("ok", "failed"):
                self.breaker.release_probe()

    def pending(self) -> int:
        """Requests submitted but without a terminal result yet (engine-
        queued requests are already tracked in ``_inflight``)."""
        return len(self._queue) + len(self._inflight)

    def progress(self) -> dict:
        """Live (non-terminal) request state as ``{rid: (token_base,
        emitted_tokens)}`` — queued entries report an empty emission.
        This is the stream a fleet router journals as PROGRESS
        checkpoints (every K tokens) and the state a hot-standby router
        adopts at takeover: a copy whose ``token_base`` is within the
        journaled prefix keeps running; anything else is cancelled and
        resubmitted from the last checkpoint, bit-identically."""
        out = {}
        for entry in self._queue:
            out[entry.rid] = (int(entry.token_base),
                              np.zeros((0,), np.int32))
        for rid, req in self._inflight.items():
            out[rid] = (int(req.token_base),
                        np.asarray(req.output(), np.int32))
        return out

    def results(self, wait=False, timeout=None) -> dict:
        """Pop terminal results as ``{rid: RequestResult}``. With
        ``wait=True`` the frontend pumps ``step()`` until every pending
        request resolves (bounded by ``timeout`` seconds when given —
        the same per-call budget a ``RemoteFrontend`` stub honors)."""
        if wait:
            deadline = Deadline(timeout)
            while ((self.pending() or self.engine.has_work())
                   and not deadline.expired()):
                self.step()
        out, self._results = self._results, {}
        return out

    def cancel(self, rid) -> bool:
        """Cancel a queued or in-flight request; its partial tokens (if
        any) land in results with status ``"cancelled"``. Returns False
        when the rid is unknown or already terminal."""
        for entry in self._queue:
            if entry.rid == rid:
                self._queue.remove(entry)
                if telemetry.enabled():
                    _M_REQS.inc(status="cancelled")  # engine never saw it
                self._cancel_bookkeeping(rid, reason="cancelled in queue",
                                         token_base=entry.token_base)
                return True
        req = self.engine.abort(rid, "cancelled")
        if req is not None:
            self._cancel_bookkeeping(rid, tokens=req.output(),
                                     reason="cancelled in flight",
                                     token_base=req.token_base)
            return True
        return False

    # --------------------------------------- KV page transfer passthrough
    # The router drives the prefill→decode handoff against frontends
    # (local here, RemoteFrontend stubs in a fleet); these delegate to
    # the engine's primitive surface so both sides expose one API.

    def export_pages(self, rid):
        """Mint (or re-serve) the KV transfer ticket for ``rid``'s held
        prefill pages (see ``ContinuousBatchingEngine.export_pages``)."""
        return self.engine.export_pages(rid)

    def transfer_chunk(self, ticket, idx):
        """Serve one CRC-framed chunk of a live export."""
        return self.engine.transfer_chunk(ticket, idx)

    def import_kv_chunk(self, meta, idx, payk, payv, crc):
        """Land one chunk of an inbound transfer (idempotent by
        ticket + index)."""
        return self.engine.import_kv_chunk(meta, idx, payk, payv, crc)

    def release_export(self, ticket) -> bool:
        """Drop a finished/abandoned export's page pin (idempotent)."""
        return self.engine.release_export(ticket)

    def drop_import(self, ticket) -> bool:
        """Abandon a partial inbound transfer, freeing its local page
        grants (idempotent)."""
        return self.engine.drop_import(ticket)

    # ------------------------------------------------------------ shutdown

    def shutdown(self, drain=True):
        """Stop admitting. ``drain=True`` finishes the requests already
        holding slots (their results arrive normally) and reports
        ``"cancelled"`` for everything still queued; ``drain=False`` also
        cancels the in-flight slots, keeping their partial tokens."""
        if self._closed:
            return
        self._draining = True
        for entry in self._queue:
            if telemetry.enabled():
                _M_REQS.inc(status="cancelled")  # engine never saw it
            self._cancel_bookkeeping(entry.rid,
                                     reason="shutdown before admission",
                                     token_base=entry.token_base)
        self._queue.clear()
        for req in self.engine.queued_requests():
            self.engine.abort(req.rid, "cancelled")
            self._cancel_bookkeeping(req.rid, tokens=req.output(),
                                     reason="shutdown before a slot was "
                                            "assigned",
                                     token_base=req.token_base)
        if drain:
            # the drain pump stays under the watchdog scope: a dispatch
            # that wedges DURING shutdown still trips the timeout dump
            while self.engine.has_work():
                self._watched(lambda: self._record(self.engine.step()))
        else:
            for req in list(self.engine.active_requests()):
                self.engine.abort(req.rid, "cancelled")
                self._cancel_bookkeeping(req.rid, tokens=req.output(),
                                         reason="shutdown cancelled "
                                                "in-flight",
                                         token_base=req.token_base)
            # cancelling in-flight slots can strand a dispatched-but-
            # unconsumed pipeline segment; drain it so the engine ends
            # the session clean (its emissions are discarded — every
            # request is already terminal)
            while self.engine.has_work():
                self._watched(lambda: self._record(self.engine.step()))
        self._closed = True

    # -------------------------------------------------------------- health

    def ready(self) -> bool:
        """Admitting traffic right now? (False while draining, stopped,
        or with the breaker open — the state an elastic watchdog polls
        before routing work here.)"""
        return (not self._closed and not self._draining
                and self.breaker.state() != CircuitBreaker.OPEN)

    def health(self) -> dict:
        """Snapshot for watchdogs and load-balancers — ONE machine-readable
        payload (plain ints/floats/strings only) with everything a router
        needs to score and gate this replica:

        * overall ``state`` (``ok | degraded | draining | unavailable |
          stopped``) and ``ready``;
        * breaker detail: ``breaker`` state plus ``breaker_failures``
          (consecutive failures while closed — a replica drifting toward
          its trip point scores worse before it trips);
        * load: ``queue_depth`` / ``queued_tokens`` backlog,
          ``queue_by_priority`` per request class (``{priority: [depth,
          queued_tokens]}``), and ``inflight`` (admitted to the engine,
          not yet terminal);
        * KV-slot occupancy: ``active_slots`` / ``free_slots`` /
          ``kv_slots`` (total) / ``kv_occupancy`` (active/total); page
          POOL pressure: ``kv_pages_free`` / ``kv_pages_total`` /
          ``kv_fragmentation_pct`` / ``prefix_hit_rate`` (the dynamic
          allocator's admission headroom — a router can prefer replicas
          with page headroom, not just free slots);
        * ``latency``: recent-window percentile summaries (p50/p95/p99 +
          count/mean, seconds) for TTFT, per-token decode latency, and
          admission-queue wait — sourced from the telemetry registry
          histograms (``serving.ttft_s`` / ``serving.token_latency_s`` /
          ``serving.queue_wait_s``), which are PROCESS-scoped: in a
          one-replica-per-process fleet this is the replica's view.
        """
        breaker_state = self.breaker.state()
        if self._closed:
            state = "stopped"
        elif self._draining:
            state = "draining"
        elif breaker_state == CircuitBreaker.OPEN:
            state = "unavailable"
        elif breaker_state == CircuitBreaker.HALF_OPEN:
            state = "degraded"
        else:
            state = "ok"
        by_prio: dict[int, list] = {}
        by_tenant: dict[str, list] = {}
        for e in self._queue:
            row = by_prio.setdefault(int(e.priority), [0, 0])
            row[0] += 1
            row[1] += e.cost
            trow = by_tenant.setdefault(tenant_label(e.tenant), [0, 0])
            trow[0] += 1
            trow[1] += e.cost
        active = len(self.engine.active_requests())
        total = int(self.engine.max_slots)
        kv = (self.engine.kv_stats()
              if hasattr(self.engine, "kv_stats") else {})
        return {
            "state": state,
            "ready": self.ready(),
            "role": self.role,
            "breaker": breaker_state,
            "breaker_failures": self.breaker.failures,
            "draining": self._draining,
            "queue_depth": len(self._queue),
            "queued_tokens": self.queued_tokens(),
            "queue_by_priority": by_prio,
            "queue_by_tenant": by_tenant,
            # per-tenant OUTSTANDING token cost (queued + in-flight):
            # the quantity quota_tokens bounds
            "tenant_outstanding": {tenant_label(t): int(c)
                                   for t, c in self._tenant_out.items()},
            "inflight": len(self._inflight),
            "active_slots": active,
            "free_slots": self.engine.free_slots(),
            "kv_slots": total,
            "kv_occupancy": (active / total) if total else 0.0,
            "kv_pages_free": int(kv.get("pages_free", 0)),
            "kv_pages_total": int(kv.get("pages_total", 0)),
            "kv_fragmentation_pct": float(
                kv.get("fragmentation_pct", 0.0)),
            "prefix_hit_rate": float(kv.get("prefix_hit_rate", 0.0)),
            "kv_admission_blocked": bool(
                getattr(self.engine, "admission_blocked", False)),
            "latency": latency_summaries(),
            # perfwatch SLO verdict: objectives, rolling goodput,
            # multi-window burn rate, the alarm the shedding flag acts on
            "slo": (self.slo.status() if telemetry.enabled() else {}),
            # brownout ladder stage (0 unless FLAGS_brownout engaged it)
            "brownout": self.brownout.status(),
        }
