"""Version shims for jax APIs the SPMD modules use.

``shard_map`` graduated from ``jax.experimental`` to the top level, and
the explicit varying-manual-axes (vma) type system added ``lax.pcast``;
older jax releases have neither. These shims let ``ring_attention`` /
``pipeline`` run unchanged on both sides:

* :func:`shard_map` — the top-level one when present, else the
  experimental one with ``check_rep=False`` (the old replication checker
  has no rules for the manual ppermute accumulation patterns these
  modules build; the new vma system types them fine).
* :func:`pcast` — marks a value device-varying over ``axes`` under the
  vma type system; a no-op identity on jax without one (nothing tracks
  variance there, so there is nothing to cast).
"""
import jax
from jax import lax as _lax

try:  # jax with top-level shard_map (vma typing)
    from jax import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
except ImportError:  # older jax: experimental, pre-vma
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)


def pcast(x, axes, to):
    if hasattr(_lax, "pcast"):
        return _lax.pcast(x, axes, to=to)
    return x


def axis_size(name):
    """``lax.axis_size`` where it exists; the psum-of-one identity (a
    static constant — jax folds it) everywhere else."""
    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(name)
    return _lax.psum(1, name)


__all__ = ["shard_map", "pcast", "axis_size"]
