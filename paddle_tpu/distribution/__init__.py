"""paddle_tpu.distribution — probability distributions.

Analog of /root/reference/python/paddle/distribution/ (~25 distributions,
transforms, kl registry). Sampling uses the framework RNG
(core/random.py); densities are jnp and differentiable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random
from ..core.tensor import Tensor

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
    "Beta", "Dirichlet", "Exponential", "Gamma", "Geometric", "Gumbel",
    "Laplace", "LogNormal", "Multinomial", "Poisson",
    "Binomial", "Cauchy", "Chi2", "ContinuousBernoulli",
    "ExponentialFamily", "Independent", "LKJCholesky",
    "MultivariateNormal", "StudentT", "TransformedDistribution",
    "kl_divergence", "register_kl", "transform",
]


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _t(v):
    return Tensor._from_value(v)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _t(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale**2, self.batch_shape))

    @property
    def stddev(self):
        return _t(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        key = _random.next_key()
        eps = jax.random.normal(key, tuple(shape) + self.batch_shape)
        return _t(self.loc + self.scale * eps)

    rsample = sample

    def log_prob(self, value):
        var = self.scale**2
        return _t(-((_v(value) - self.loc) ** 2) / (2 * var)
                  - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))


class LogNormal(Normal):
    def sample(self, shape=()):
        return _t(jnp.exp(_v(super().sample(shape))))

    def log_prob(self, value):
        x = _v(value)
        return _t(_v(super().log_prob(jnp.log(x))) - jnp.log(x))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        return _t(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        x = _v(value)
        inside = (x >= self.low) & (x < self.high)
        lp = -jnp.log(self.high - self.low)
        return _t(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None and probs is None:
            self.logits = _v(logits)
        elif probs is not None:
            self.logits = jnp.log(_v(probs))
        else:
            raise ValueError("need logits or probs")
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return _t(jax.nn.softmax(self.logits, -1))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        logp = jax.nn.log_softmax(self.logits, -1)
        idx = _v(value).astype(jnp.int32)
        return _t(jnp.take_along_axis(logp, idx[..., None], -1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return _t(-jnp.sum(jnp.exp(logp) * logp, -1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.bernoulli(
            key, self.probs_, tuple(shape) + self.batch_shape
        ).astype(jnp.float32))

    def log_prob(self, value):
        x = _v(value)
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return _t(x * jnp.log(p) + (1 - x) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return _t(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.exponential(
            key, tuple(shape) + self.batch_shape) / self.rate)

    def log_prob(self, value):
        return _t(jnp.log(self.rate) - self.rate * _v(value))

    def entropy(self):
        return _t(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.gamma(
            key, self.concentration,
            tuple(shape) + self.batch_shape) / self.rate)

    def log_prob(self, value):
        x = _v(value)
        a, b = self.concentration, self.rate
        return _t(a * jnp.log(b) + (a - 1) * jnp.log(x) - b * x
                  - jax.lax.lgamma(a))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.beta(
            key, self.alpha, self.beta, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        x = _v(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                 - jax.lax.lgamma(a + b))
        return _t((a - 1) * jnp.log(x) + (b - 1) * jnp.log1p(-x) - lbeta)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.dirichlet(
            key, self.concentration, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        a = self.concentration
        x = _v(value)
        lnorm = jnp.sum(jax.lax.lgamma(a), -1) - jax.lax.lgamma(jnp.sum(a, -1))
        return _t(jnp.sum((a - 1) * jnp.log(x), -1) - lnorm)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(self.loc + self.scale * jax.random.laplace(
            key, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        return _t(-jnp.abs(_v(value) - self.loc) / self.scale
                  - jnp.log(2 * self.scale))

    def entropy(self):
        return _t(1 + jnp.log(2 * self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(self.loc + self.scale * jax.random.gumbel(
            key, tuple(shape) + self.batch_shape))

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape)

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, tuple(shape) + self.batch_shape)
        return _t(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        return _t(_v(value) * jnp.log1p(-self.probs_) + jnp.log(self.probs_))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    def sample(self, shape=()):
        key = _random.next_key()
        return _t(jax.random.poisson(
            key, self.rate, tuple(shape) + self.batch_shape
        ).astype(jnp.float32))

    def log_prob(self, value):
        x = _v(value)
        return _t(x * jnp.log(self.rate) - self.rate
                  - jax.lax.lgamma(x + 1.0))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    def sample(self, shape=()):
        cat = Categorical(probs=self.probs_)
        draws = _v(cat.sample(tuple(shape) + (self.total_count,)))
        k = self.probs_.shape[-1]
        onehot = jax.nn.one_hot(draws, k)
        return _t(jnp.sum(onehot, axis=-2))

    def log_prob(self, value):
        x = _v(value)
        logp = jnp.log(self.probs_)
        coeff = (jax.lax.lgamma(jnp.asarray(self.total_count + 1.0))
                 - jnp.sum(jax.lax.lgamma(x + 1.0), -1))
        return _t(coeff + jnp.sum(x * logp, -1))


# ------------------------------------------------------------ KL registry

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (pc, qc), f in _KL_REGISTRY.items():
            if isinstance(p, pc) and isinstance(q, qc):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(
            f"KL({type(p).__name__} || {type(q).__name__}) not registered")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _t(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return _t(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _t(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    return _t(a * (jnp.log(a) - jnp.log(b))
              + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = p.rate / q.rate
    return _t(jnp.log(r) + q.rate / p.rate - 1.0)


def _digamma(x):
    return jax.lax.digamma(x)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    a1, b1, a2, b2 = (jnp.asarray(p.concentration, jnp.float32),
                      jnp.asarray(p.rate, jnp.float32),
                      jnp.asarray(q.concentration, jnp.float32),
                      jnp.asarray(q.rate, jnp.float32))
    a1, b1, a2, b2 = jnp.broadcast_arrays(a1, b1, a2, b2)
    return _t((a1 - a2) * _digamma(a1) - jax.lax.lgamma(a1)
              + jax.lax.lgamma(a2) + a2 * (jnp.log(b1) - jnp.log(b2))
              + a1 * (b2 - b1) / b1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    a1, b1 = jnp.broadcast_arrays(jnp.asarray(p.alpha, jnp.float32),
                                  jnp.asarray(p.beta, jnp.float32))
    a2, b2 = jnp.broadcast_arrays(jnp.asarray(q.alpha, jnp.float32),
                                  jnp.asarray(q.beta, jnp.float32))
    lbeta = lambda a, b: (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                          - jax.lax.lgamma(a + b))
    s1 = a1 + b1
    return _t(lbeta(a2, b2) - lbeta(a1, b1)
              + (a1 - a2) * _digamma(a1) + (b1 - b2) * _digamma(b1)
              + (a2 - a1 + b2 - b1) * _digamma(s1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    a = jnp.asarray(p.concentration, jnp.float32)
    b = jnp.asarray(q.concentration, jnp.float32)
    s = jnp.sum(a, -1)
    return _t(jax.lax.lgamma(s) - jnp.sum(jax.lax.lgamma(a), -1)
              - jax.lax.lgamma(jnp.sum(b, -1))
              + jnp.sum(jax.lax.lgamma(b), -1)
              + jnp.sum((a - b) * (_digamma(a) - _digamma(s)[..., None]), -1))


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    r = p.scale / q.scale
    return _t(-jnp.log(r) + d / q.scale
              + r * jnp.exp(-d / p.scale) - 1.0)


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    a = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    return _t((1 - a) / a * (jnp.log1p(-a) - jnp.log1p(-b))
              + jnp.log(a) - jnp.log(b))


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _t(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
              + q.rate - p.rate)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal(p, q)


# second-tranche distributions + their KL pairs live in extra.py/transform.py
from . import transform  # noqa: E402
from .extra import (  # noqa: E402
    Binomial, Cauchy, Chi2, ContinuousBernoulli, ExponentialFamily,
    Independent, LKJCholesky, MultivariateNormal, StudentT,
    TransformedDistribution,
)


@register_kl(Binomial, Binomial)
def _kl_binomial(p, q):
    # validate only when counts are concrete; under jit tracing the caller
    # owns the invariant (a host-side equality check would break tracing)
    if not any(isinstance(c, jax.core.Tracer)
               for c in (p.total_count, q.total_count)):
        if not bool(np.all(np.asarray(p.total_count)
                           == np.asarray(q.total_count))):
            raise NotImplementedError(
                "KL(Binomial||Binomial) requires equal total_count")
    a = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    per_trial = (a * (jnp.log(a) - jnp.log(b))
                 + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))
    return _t(p.total_count * per_trial)


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    num = (p.scale + q.scale) ** 2 + (p.loc - q.loc) ** 2
    den = 4 * p.scale * q.scale
    return _t(jnp.log(num / den))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    k = p.event_shape[0]
    diff = q.loc - p.loc
    batch = jnp.broadcast_shapes(p.scale_tril.shape[:-2],
                                 q.scale_tril.shape[:-2], diff.shape[:-1])
    Lp = jnp.broadcast_to(p.scale_tril, batch + (k, k))
    Lq = jnp.broadcast_to(q.scale_tril, batch + (k, k))
    diff = jnp.broadcast_to(diff, batch + (k,))
    m = jax.scipy.linalg.solve_triangular(Lq, Lp, lower=True)
    tr = jnp.sum(m * m, (-2, -1))
    md = jax.scipy.linalg.solve_triangular(
        Lq, diff[..., None], lower=True)[..., 0]
    maha = jnp.sum(md * md, -1)
    hld = (jnp.sum(jnp.log(jnp.diagonal(Lq, axis1=-2, axis2=-1)), -1)
           - jnp.sum(jnp.log(jnp.diagonal(Lp, axis1=-2, axis2=-1)), -1))
    return _t(0.5 * (tr + maha - k) + hld)


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p.rank != q.rank:
        raise NotImplementedError("Independent KL needs matching ranks")
    inner = kl_divergence(p.base, q.base)
    v = inner._value if isinstance(inner, Tensor) else jnp.asarray(inner)
    if p.rank:
        v = jnp.sum(v, axis=tuple(range(-p.rank, 0)))
    return _t(v)
