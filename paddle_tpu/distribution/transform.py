"""Bijective transforms for TransformedDistribution.

Analog of /root/reference/python/paddle/distribution/transform.py (14
transform classes: Abs/Affine/Chain/Exp/Independent/Power/Reshape/
Sigmoid/Softmax/Stack/StickBreaking/Tanh over a Transform base). Each
transform is a deterministic jnp map with forward, inverse, and log-det
Jacobian; everything is traceable/differentiable.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


# share the Tensor box/unbox helpers with the sibling distribution modules
# (the package __init__ defines them before importing this module)
from . import _t, _v  # noqa: E402


class Transform:
    """Base class: y = f(x) with tractable inverse and log|det J|."""

    #: number of event dims the transform consumes (0 = elementwise)
    _domain_event_rank = 0
    _codomain_event_rank = 0
    bijective = True

    def forward(self, x):
        return _t(self._forward(_v(x)))

    def inverse(self, y):
        return _t(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._forward_log_det_jacobian(_v(x)))

    def inverse_log_det_jacobian(self, y):
        y = _v(y)
        return _t(-self._forward_log_det_jacobian(self._inverse(y)))

    def __call__(self, x):
        return self.forward(x)

    # hooks ------------------------------------------------------------
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| — not bijective; inverse returns the positive branch."""

    bijective = False

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power  (x > 0)."""

    def __init__(self, power):
        self.power = _v(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (not bijective on R^n)."""

    _domain_event_rank = 1
    _codomain_event_rank = 1
    bijective = False

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        x = jnp.log(y)
        return x - x.max(-1, keepdims=True)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("SoftmaxTransform is not bijective")


class StickBreakingTransform(Transform):
    """R^{n} -> interior of the n+1 simplex via stick breaking."""

    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        cum = jnp.cumprod(1 - z, -1)
        pad = jnp.ones_like(cum[..., :1])
        lead = jnp.concatenate([pad, cum[..., :-1]], -1)
        head = z * lead
        last = cum[..., -1:]
        return jnp.concatenate([head, last], -1)

    def _inverse(self, y):
        n = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - cum
        pad = jnp.ones_like(rem[..., :1])
        lead = jnp.concatenate([pad, rem[..., :-1]], -1)
        z = y[..., :-1] / lead
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        n = x.shape[-1]
        offset = jnp.log(jnp.arange(n, 0, -1, dtype=x.dtype))
        xs = x - offset
        z = jax.nn.sigmoid(xs)
        cum = jnp.cumprod(1 - z, -1)
        pad = jnp.ones_like(cum[..., :1])
        lead = jnp.concatenate([pad, cum[..., :-1]], -1)
        # dy_k/dz_k = lead_k; dz/dx = sigmoid'(xs)
        return jnp.sum(jnp.log(lead) - jax.nn.softplus(-xs)
                       - jax.nn.softplus(xs), -1)


class ChainTransform(Transform):
    """Composition t_k ∘ … ∘ t_1 (applied left to right)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        # propagate event ranks through rank-changing members: the chain's
        # domain rank is found walking backward from the last member, the
        # codomain rank by replaying forward
        r = 0
        for t in reversed(self.transforms):
            r = max(t._domain_event_rank,
                    r - t._codomain_event_rank + t._domain_event_rank)
        self._domain_event_rank = r
        for t in self.transforms:
            r = max(r - t._domain_event_rank + t._codomain_event_rank,
                    t._codomain_event_rank)
        self._codomain_event_rank = r

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = None
        r = self._domain_event_rank
        for t in self.transforms:
            ldj = t._forward_log_det_jacobian(x)
            # reduce ldj over dims that are event dims at this point in the
            # chain but batch dims to this member
            extra = r - t._domain_event_rank
            if extra > 0:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = ldj if total is None else total + ldj
            x = t._forward(x)
            r = r - t._domain_event_rank + t._codomain_event_rank
        return total


class IndependentTransform(Transform):
    """Reinterpret trailing batch dims of a base transform as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_rank = base._domain_event_rank + self.rank
        self._codomain_event_rank = base._codomain_event_rank + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        return jnp.sum(ldj, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if math.prod(self.in_event_shape) != math.prod(self.out_event_shape):
            raise ValueError("event sizes must match")
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class StackTransform(Transform):
    """Apply transforms[i] to slice i along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _split(self, x):
        return [jnp.squeeze(s, self.axis)
                for s in jnp.split(x, len(self.transforms), self.axis)]

    def _forward(self, x):
        parts = [t._forward(s) for t, s in zip(self.transforms, self._split(x))]
        return jnp.stack(parts, self.axis)

    def _inverse(self, y):
        parts = [t._inverse(s) for t, s in zip(self.transforms, self._split(y))]
        return jnp.stack(parts, self.axis)

    def _forward_log_det_jacobian(self, x):
        parts = [t._forward_log_det_jacobian(s)
                 for t, s in zip(self.transforms, self._split(x))]
        return jnp.stack(parts, self.axis)
