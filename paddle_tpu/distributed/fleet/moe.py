"""Mixture-of-Experts layer with GShard-style capacity dispatch.

Analog of /root/reference/python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 (``MoELayer``) and its gates (gate/naive_gate.py,
switch_gate.py, gshard_gate.py), plus the global_scatter/global_gather
collective ops used for expert-parallel dispatch.

TPU-native dispatch: index-based scatter-add into the (E*C) slot space and
a weighted gather back (the global_scatter/global_gather shapes) — O(T*K)
routing state, never a dense (T, E, C) combine tensor.

Expert parallelism: with stacked experts on an ``ep`` mesh axis, the
forward runs an EXPLICIT shard_map EP exchange — tokens sharded over
``ep``, each device dispatches its local tokens into per-(rank, expert)
capacity slots, ``lax.all_to_all`` moves the slots to the experts' owners
and back (the literal global_scatter/global_gather pair,
moe_layer.py:263) — so dispatch bandwidth stays at T*D/ep instead of the
full all-gather GSPMD falls back to when left to propagate the scatter on
its own (verified by HLO inspection in tests/test_fleet.py).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn.layers_common import LayerList
from ...ops import registry as _registry

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate"]


def _moe_dispatch_kernel(x, gate_logits, capacity, top_k):
    """tokens (T, D) + logits (T, E) -> dispatched (E, C, D), routing
    indices (K, T) into the flattened (E*C) slot space (-1 = dropped),
    routing weights (K, T), aux load-balance loss.

    Index-based formulation (the reference's global_scatter shape): the
    dispatch is a scatter-add into E*C slots and the combine a gather —
    O(T*K) routing state instead of the dense (T, E, C) one-hot combine
    tensor of the GShard-einsum formulation, which at real scale
    (T=8192, E=64, C≈1.25T/E) is memory-hostile. Pure jnp; registered as
    an op so eager calls are jit-cached and gradients flow via jax.vjp
    (scatter-add/gather transpose to each other)."""
    import jax

    T, D = x.shape
    E = gate_logits.shape[1]
    C = capacity
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    probs = probs.astype(x.dtype)  # (T, E)

    remaining = probs
    position_in_expert = jnp.zeros((E,), jnp.int32)
    slot_rounds = []
    weight_rounds = []
    first_mask = None
    # iterative top-k with capacity (GShard top-2 when top_k=2)
    for r in range(top_k):
        idx = jnp.argmax(remaining, axis=1)                      # (T,)
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)         # (T, E)
        pos = jnp.cumsum(onehot, axis=0) - 1 + position_in_expert[None, :]
        pos_tok = jnp.sum(pos * onehot, axis=1)                  # (T,)
        fits = pos_tok < C
        w = jnp.sum(probs * onehot, axis=1) * fits               # (T,)
        slot = jnp.where(fits, idx * C + jnp.clip(pos_tok, 0, C - 1), -1)
        slot_rounds.append(slot.astype(jnp.int32))
        weight_rounds.append(w)
        position_in_expert = position_in_expert + jnp.sum(
            onehot * fits[:, None], axis=0)
        remaining = remaining * (1 - onehot)
        if first_mask is None:
            first_mask = onehot

    slots = jnp.stack(slot_rounds)        # (K, T)
    weights = jnp.stack(weight_rounds)    # (K, T)

    # load-balance aux loss (GShard eq.4): E * mean(frac_tokens * frac_prob)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(first_mask.astype(jnp.float32), axis=0)
    aux = jnp.sum(me * ce) * E

    # dispatch = scatter-add into the E*C slot space; dropped tokens go to
    # a discarded overflow row (no masking needed — the slice drops them,
    # and its transpose gives those tokens a zero cotangent)
    flat = jnp.zeros((E * C + 1, D), x.dtype)
    for r in range(top_k):
        tgt = jnp.where(slots[r] >= 0, slots[r], E * C)
        flat = flat.at[tgt].add(x)
    dispatched = flat[:E * C].reshape(E, C, D)
    return dispatched, slots, weights, aux


_registry.register_op(
    "moe_dispatch", _moe_dispatch_kernel, inputs=("x", "gate_logits"))


def _moe_ep_kernel(x, gate_logits, w_in, w_out, *, mesh, ep_axis, capacity,
                   top_k, activation):
    """Expert-parallel MoE forward as ONE shard_map program over ``ep``:
    local dispatch -> all_to_all (global_scatter) -> local stacked-expert
    FFN -> all_to_all (global_gather) -> local combine. ``capacity`` is
    per (source rank, expert); the per-expert total is ``ep * capacity``,
    matching the replicated kernel's global capacity."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    E = gate_logits.shape[1]
    ep = mesh.shape[ep_axis]
    E_loc = E // ep

    def body(x_loc, lg_loc, w_in_loc, w_out_loc):
        T_loc, D = x_loc.shape
        dispatched, slots, weights, aux = _moe_dispatch_kernel(
            x_loc, lg_loc, capacity, top_k)             # (E, C, D) local
        # global_scatter: destination-rank-major blocks, transposed so
        # rank r receives every source's slots for ITS experts
        d = dispatched.reshape(ep, E_loc, capacity, D)
        d = jax.lax.all_to_all(d, ep_axis, split_axis=0, concat_axis=0,
                               tiled=True)              # (ep, E_loc, C, D)
        d = d.transpose(1, 0, 2, 3).reshape(E_loc, ep * capacity, D)
        # exact-erf gelu to match the replicated path's F.gelu
        # (jax.nn.gelu defaults to the tanh approximation)
        act = {"gelu": lambda v: jax.nn.gelu(v, approximate=False)}.get(
            activation) or getattr(jax.nn, activation)
        h = act(jnp.einsum("ecd,edh->ech", d, w_in_loc))
        out_e = jnp.einsum("ech,ehd->ecd", h, w_out_loc)
        # global_gather: send each source rank its tokens' outputs back
        g = out_e.reshape(E_loc, ep, capacity, D).transpose(1, 0, 2, 3)
        g = jax.lax.all_to_all(g, ep_axis, split_axis=0, concat_axis=0,
                               tiled=True)              # (ep, E_loc, C, D)
        expert_out = g.reshape(E, capacity, D)          # global expert order
        yf = _combine_kernel(slots, weights, expert_out)
        return yf, jax.lax.pmean(aux, ep_axis)

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(ep_axis), P(ep_axis), P(ep_axis), P(ep_axis)),
        out_specs=(P(ep_axis), P()),
    )(x, gate_logits, w_in, w_out)


_registry.register_op("moe_ep_forward", _moe_ep_kernel,
                      inputs=("x", "gate_logits", "w_in", "w_out"))


class NaiveGate(Layer):
    """Linear router, top-k (reference gate/naive_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=2):
        super().__init__()
        from ...nn.layers_common import Linear

        self.top_k = top_k
        self.gate = Linear(d_model, num_expert * world_size)

    def forward(self, x):
        return self.gate(x)


class SwitchGate(NaiveGate):
    """Top-1 routing (reference gate/switch_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, top_k=1):
        super().__init__(d_model, num_expert, world_size, top_k=1)


GShardGate = NaiveGate


class MoELayer(Layer):
    """MoE block: route tokens to experts, run experts, combine.

    moe_layer.py:263 semantics: ``experts`` is a list of Layers (one per
    local expert); ``gate`` a Gate layer or config dict. Capacity factor
    bounds tokens per expert; overflow tokens contribute ZERO output (add
    a residual connection around the layer if pass-through is wanted).
    """

    def __init__(self, d_model, experts=None, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, capacity_factor=1.25,
                 top_k=None, **kwargs):
        super().__init__()
        self.d_model = d_model
        self._stacked = None
        if isinstance(experts, (list, LayerList)):
            self.experts = (experts if isinstance(experts, LayerList)
                            else LayerList(list(experts)))
            self.num_experts = len(self.experts)
        elif isinstance(experts, Layer) and hasattr(experts, "num_experts"):
            self._stacked = experts
            self.experts = LayerList([experts])
            self.num_experts = experts.num_experts
        else:
            raise ValueError(
                "experts must be a list of Layers or a stacked-expert Layer")
        if gate is None or isinstance(gate, dict):
            cfg = gate or {}
            top = cfg.get("top_k", top_k or 2)
            typ = cfg.get("type", "naive")
            cls = SwitchGate if typ == "switch" else NaiveGate
            self.gate = cls(d_model, self.num_experts, top_k=top)
        else:
            self.gate = gate
        self.top_k = getattr(self.gate, "top_k", top_k or 2)
        self.capacity_factor = capacity_factor
        self.aux_loss = None

    def forward(self, x):
        from ...ops import reshape

        orig_shape = x.shape
        T = int(np.prod(orig_shape[:-1]))
        xf = reshape(x, [T, self.d_model])
        logits = self.gate(xf)
        capacity = max(int(self.capacity_factor * T / self.num_experts), 1)

        ep_cfg = getattr(self._stacked, "_ep", None)
        if ep_cfg is not None:
            jmesh, ep_axis = ep_cfg
            ep = jmesh.shape[ep_axis]
            if T % ep == 0 and self.num_experts % ep == 0:
                # explicit EP: per-(rank, expert) capacity, all_to_all
                # dispatch/return (global_scatter/global_gather)
                cap_loc = max(
                    int(self.capacity_factor * (T // ep) / self.num_experts),
                    1)
                yf, aux = _registry.apply_op(
                    _registry.get_op("moe_ep_forward"), xf, logits,
                    self._stacked.w_in, self._stacked.w_out,
                    mesh=jmesh, ep_axis=ep_axis, capacity=cap_loc,
                    top_k=self.top_k, activation=self._stacked.activation)
                self.aux_loss = aux
                return reshape(yf, list(orig_shape))

        dispatched, slots, weights, aux = _registry.apply_op(
            _registry.get_op("moe_dispatch"), xf, logits,
            capacity=capacity, top_k=self.top_k)
        self.aux_loss = aux

        if self._stacked is not None:
            # batched path: all experts in one einsum (ep-shardable)
            expert_out = self._stacked(dispatched)
        else:
            # per-expert loop (E small; static unroll under jit)
            outs = []
            for e, expert in enumerate(self.experts):
                outs.append(expert(dispatched[e]))
            from ...ops import stack

            expert_out = stack(outs, axis=0)  # (E, C, D)
        yf = _combine(slots, weights, expert_out)
        return reshape(yf, list(orig_shape))


def _combine_kernel(slots, weights, expert_out):
    """Gather each token's expert outputs from its (K, T) slots and weight
    them — the global_gather shape. Dropped tokens (slot -1) already carry
    weight 0, so a clipped gather suffices (no zero-row concat copy)."""
    E, C, D = expert_out.shape
    flat = expert_out.reshape(E * C, D)
    out = 0.0
    for r in range(slots.shape[0]):
        tgt = jnp.clip(slots[r], 0, E * C - 1)
        out = out + weights[r][:, None] * flat[tgt]
    return out


_registry.register_op(
    "moe_combine", _combine_kernel,
    inputs=("slots", "weights", "expert_out"))


def _combine(slots, weights, expert_out):
    return _registry.apply_op(
        _registry.get_op("moe_combine"), slots, weights, expert_out)


class StackedExpertsFFN(Layer):
    """Expert-parallel FFN with *stacked* weights: gate/up/down carry a
    leading expert dim, shardable Shard(0) over the ``ep`` mesh axis, and
    all experts run as one batched einsum — the vmap form the reference's
    fused_moe kernel implements in CUDA. Pair with MoELayer via
    ``experts=StackedExpertsFFN(...)`` (it is called with the dispatched
    (E, C, D) tensor directly)."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu",
                 mesh=None, ep_axis="ep"):
        super().__init__()
        from ...nn import initializer as I

        self.num_experts = num_experts
        self.w_in = self.create_parameter(
            (num_experts, d_model, d_hidden),
            default_initializer=I.XavierNormal())
        self.w_out = self.create_parameter(
            (num_experts, d_hidden, d_model),
            default_initializer=I.XavierNormal())
        self.activation = activation
        self._ep = None
        if mesh is not None and ep_axis in mesh.dim_names:
            from ..api import shard_tensor
            from ..placement import Replicate, Shard

            pl = [Replicate()] * mesh.ndim
            pl[mesh.dim_names.index(ep_axis)] = Shard(0)
            shard_tensor(self.w_in, mesh, pl)
            shard_tensor(self.w_out, mesh, pl)
            self._ep = (mesh.jax_mesh(), ep_axis)

    def forward(self, dispatched):
        """(E, C, D) -> (E, C, D), one batched matmul pair over experts."""
        from ...nn import functional as F
        from ...ops import registry as _reg

        act = getattr(F, self.activation)
        h = _reg.apply_op(_reg.get_op("_moe_expert_mm"), dispatched, self.w_in)
        h = act(h)
        return _reg.apply_op(_reg.get_op("_moe_expert_mm"), h, self.w_out)


def _moe_expert_mm_kernel(x, w):
    return jnp.einsum("ecd,edh->ech", x, w)


_registry.register_op("_moe_expert_mm", _moe_expert_mm_kernel,
                      inputs=("x", "w"))
