"""incubate.optimizer — ModelAverage + LookAhead.

Analogs of /root/reference/python/paddle/incubate/optimizer/
{modelaverage,lookahead}.py (kernels: average_accumulates). Both are
host-orchestrated wrappers over jnp arrays — the heavy math stays on
device, the window bookkeeping is Python.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ModelAverage", "LookAhead"]


class ModelAverage:
    """Sliding-window parameter averaging (reference modelaverage.py):
    ``step()`` after each optimizer update accumulates parameters; the
    window grows with update count as
    ``min(max(num_updates*rate, min_average_window), max_average_window)``
    and rolls over the three-sum scheme of the reference's
    average_accumulates kernel. ``apply()`` swaps averaged parameters in
    (optionally as a context manager), ``restore()`` swaps back.
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters must be provided")
        self._params = list(parameters)
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._sum1 = [jnp.zeros_like(p._value) for p in self._params]
        self._sum2 = [jnp.zeros_like(p._value) for p in self._params]
        self._sum3 = [jnp.zeros_like(p._value) for p in self._params]
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._saved = None

    def step(self):
        self._num_updates += 1
        self._num_accumulates += 1
        window = min(max(self._num_updates * self._rate, self._min_w),
                     self._max_w)
        roll = self._num_accumulates > window
        for i, p in enumerate(self._params):
            self._sum1[i] = self._sum1[i] + p._value.astype(
                self._sum1[i].dtype)
        if roll:
            # reference average_accumulates_kernel_impl.h: the finished
            # window folds into sum_3 and both live sums reset
            for i in range(len(self._params)):
                self._sum3[i] = self._sum1[i] + self._sum2[i]
                self._sum2[i] = jnp.zeros_like(self._sum2[i])
                self._sum1[i] = jnp.zeros_like(self._sum1[i])
            self._old_num_accumulates = self._num_accumulates
            self._num_accumulates = 0

    def _averaged(self, i):
        total = self._num_accumulates + self._old_num_accumulates
        if total == 0:
            return self._params[i]._value
        avg = (self._sum1[i] + self._sum2[i] + self._sum3[i]) / total
        return avg.astype(self._params[i]._value.dtype)

    def apply(self, executor=None, need_restore=True):
        self._saved = [p._value for p in self._params]
        for i, p in enumerate(self._params):
            p._value = self._averaged(i)
        if need_restore:
            return _RestoreCtx(self)
        self._saved = None
        return _RestoreCtx(None)

    def restore(self, executor=None):
        if self._saved is None:
            return
        for p, v in zip(self._params, self._saved):
            p._value = v
        self._saved = None

    def minimize(self, loss, startup_program=None):
        self.step()


class _RestoreCtx:
    def __init__(self, owner):
        self._owner = owner

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._owner is not None:
            self._owner.restore()
        return False


class LookAhead:
    """k-step lookahead (reference lookahead.py): the wrapped optimizer
    advances fast weights; every ``k`` steps the slow weights move
    ``alpha`` of the way toward them and the fast weights reset onto the
    slow track."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._params = list(inner_optimizer._parameter_list)
        self._slow = [p._value for p in self._params]
        self._k_count = 0

    def step(self):
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for i, p in enumerate(self._params):
                slow = (self._slow[i].astype(jnp.float32)
                        + self.alpha * (p._value.astype(jnp.float32)
                                        - self._slow[i].astype(jnp.float32)))
                self._slow[i] = slow.astype(p._value.dtype)
                p._value = self._slow[i]

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["@lookahead_k_count"] = self._k_count
        for i, v in enumerate(self._slow):
            out[f"lookahead_slow@{i}"] = Tensor._from_value(v)
        return out

    def set_state_dict(self, state):
        rest = {}
        for k, v in state.items():
            if k == "@lookahead_k_count":
                self._k_count = int(v)
            elif k.startswith("lookahead_slow@"):
                i = int(k.split("@")[1])
                self._slow[i] = (v._value if isinstance(v, Tensor)
                                 else jnp.asarray(v))
            else:
                rest[k] = v
        self.inner_optimizer.set_state_dict(rest)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
