"""Dynamic paged-KV allocator + copy-on-write prefix caching (ISSUE 14).

The engine's slot->page map is a free-list :class:`kv_pool.PagePool`:
pages are granted at admission, appended as decode crosses page
boundaries, freed at retirement; admission is bounded by available
pages (``serving.kv_pool_exhausted`` deferral) and a running decode is
never failed — under pool pressure the youngest slot is PREEMPTED back
to the queue and resumes bit-identically. Prompt prefixes are shared
copy-on-write through a content-verified chained-hash
:class:`kv_pool.PrefixCache`.

Load-bearing invariants drilled here:

* token streams BIT-IDENTICAL to the unshared engine — greedy and
  sampled, serial and pipelined, across CoW mid-page divergence,
  chunked-prefill resume, pool-exhausted deferral, and preemption;
* refcounts keep shared pages alive across the owners' retirements;
* ZERO post-warmup XLA compiles through the allocator/prefix path;
* ``serving.engine_fault`` bisection still isolates poison requests
  and leaks no pages;
* the PR 12 TP engine serves sharded dynamic pools bit-identically.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience, telemetry
from paddle_tpu.core.flags import set_flags
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.frontend import ServingFrontend
from paddle_tpu.models.kv_pool import PagePool, PrefixCache
from paddle_tpu.models.serving import ContinuousBatchingEngine


@pytest.fixture(autouse=True)
def _clean(tmp_path):
    resilience.reset_faults()
    resilience.reset_counters()
    telemetry.reset_telemetry()
    set_flags({"FLAGS_flight_dir": str(tmp_path / "flight")})
    yield
    resilience.reset_faults()
    resilience.reset_counters()
    telemetry.reset_telemetry()
    set_flags({"FLAGS_flight_dir": ""})


_CFG = LlamaConfig(vocab_size=151, hidden_size=32, intermediate_size=64,
                   num_hidden_layers=2, num_attention_heads=4,
                   max_position_embeddings=512, tie_word_embeddings=True)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(_CFG)


def _engine(model, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("page_size", 32)
    kw.setdefault("prompt_buckets", (16, 32, 64))
    kw.setdefault("seed", 7)
    return ContinuousBatchingEngine(model, **kw)


def _rng(seed=1):
    return np.random.RandomState(seed)


def _toks(rng, n):
    return rng.randint(0, 151, (n,)).astype(np.int32)


def _serve(eng, subs, segment=4, serialize_first=True):
    """Submit ``subs`` = [(rid, prompt, max_new)] and run to completion.
    ``serialize_first`` drains the first request before submitting the
    rest, so its prompt pages are cached when the others admit."""
    eng.start(segment=segment)
    reqs = []
    for i, (rid, p, new) in enumerate(subs):
        reqs.append(eng.submit(p, new, rid=rid))
        if i == 0 and serialize_first:
            while eng.has_work():
                eng.step()
    while eng.has_work():
        eng.step()
    return [np.asarray(r.tokens, np.int32) for r in reqs], reqs


# --------------------------------------------------------------- units


def test_page_pool_alloc_refcount_recycle():
    freed = []
    pool = PagePool(4)
    assert pool.available() == 4
    got = pool.alloc(3)
    assert len(got) == 3 and pool.available() == 1
    assert pool.alloc(2) is None          # short: caller defers
    pool.incref(got[0])                   # shared mapping
    dead = pool.decref(got)
    assert dead == got[1:]                # got[0] still referenced
    pool.recycle(dead)
    assert pool.available() == 3
    assert pool.decref([got[0]]) == [got[0]]
    freed.append(pool.refcount(got[0]))
    assert freed == [0]


def test_prefix_cache_match_insert_evict_verifies_tokens():
    pool = PagePool(8)
    recycled = []
    cache = PrefixCache(pool, 4, recycled.extend)
    long_p = np.arange(16, dtype=np.int32)          # 4 full pages
    pages = pool.alloc(4)
    cache.insert(long_p, pages)
    assert len(cache) == 4
    # a SHORTER prompt inside the cached prefix: full pages match, the
    # mid-page tail partial-matches the next cached page (CoW material)
    short = long_p[:11]
    hit, matched, partial = cache.match(short)
    assert hit == pages[:2] and matched == 8
    assert partial is not None and partial.r == 3
    assert partial.page == pages[2]
    # content is VERIFIED: a hash chain can never alias foreign tokens
    other = long_p[:11].copy()
    other[2] = 99
    hit2, matched2, partial2 = cache.match(other)
    assert hit2 == [] and matched2 == 0
    assert partial2 is not None and partial2.r == 2  # head of page 0
    # eviction is leaf-first LRU and only frees unreferenced pages
    pool.recycle(pool.decref(pages))                # slot lets go
    freed = cache.evict(10)
    assert freed == 4 and len(cache) == 0
    assert sorted(recycled) == sorted(pages)


# -------------------------------------------- bit-exactness invariants


@pytest.mark.parametrize("pipeline", [False, True])
@pytest.mark.parametrize("do_sample", [False, True])
def test_shared_prefix_streams_bit_identical(model, pipeline, do_sample):
    """Prefix-shared streams == unshared streams, greedy + per-request
    key-stream sampling, serial + pipelined — including a full-page hit,
    a mid-page CoW divergence, and an identical-prompt replay."""
    rng = _rng(2)
    pre = _toks(rng, 48)                     # 1.5 pages of 32
    subs = [(1, np.concatenate([pre, _toks(rng, 20)]), 10),
            (2, np.concatenate([pre, _toks(rng, 9)]), 10),   # page hit
            (3, pre[:40].copy(), 10),        # inside req 1, ends MID-PAGE
            (4, np.concatenate([pre, _toks(rng, 20)]), 10)]
    kw = dict(pipeline=pipeline, do_sample=do_sample, top_k=8)
    got, _ = _serve(_engine(model, prefix_cache=True, **kw), subs)
    want, _ = _serve(_engine(model, prefix_cache=False, **kw), subs)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")


def test_cow_divergence_leaves_the_owner_intact(model):
    """A mid-page CoW admission while the prefix OWNER is still decoding:
    both streams match their unshared references (the copy really is a
    copy — the writer never touches the shared page)."""
    rng = _rng(3)
    pre = _toks(rng, 64)                     # 2 pages
    p_owner = np.concatenate([pre, _toks(rng, 8)])
    p_cow = pre[:50].copy()                  # diverges mid page 1
    for pc in (True, False):
        eng = _engine(model, prefix_cache=pc)
        eng.start(segment=2)
        owner = eng.submit(p_owner, 24, rid=1)
        eng.step()                           # owner admitted + decoding
        cow = eng.submit(p_cow, 24, rid=2)   # maps owner's pages
        while eng.has_work():
            eng.step()
        if pc:
            got = (np.asarray(owner.tokens), np.asarray(cow.tokens))
            assert eng.kv_stats()["prefix_tokens_saved"] > 0
        else:
            want = (np.asarray(owner.tokens), np.asarray(cow.tokens))
    np.testing.assert_array_equal(got[0], want[0], err_msg="owner")
    np.testing.assert_array_equal(got[1], want[1], err_msg="cow reader")


def test_refcount_survives_owner_retirement(model):
    """Shared pages outlive the request that computed them: a later
    identical-prefix request hits the cache after the owner retired, and
    the pages only return to the pool once the cache lets go."""
    rng = _rng(4)
    pre = _toks(rng, 64)
    eng = _engine(model)
    subs = [(1, np.concatenate([pre, _toks(rng, 12)]), 8),
            (2, np.concatenate([pre, _toks(rng, 5)]), 8)]
    _, reqs = _serve(eng, subs)              # serialized: 1 retires first
    assert all(r.status == "ok" for r in reqs)
    kv = eng.kv_stats()
    assert kv["prefix_tokens_saved"] >= 64   # req 2 skipped the prefix
    assert kv["prefix_cached_pages"] > 0
    # every non-cache reference was released at retirement
    assert (kv["pages_free"] + kv["prefix_cached_pages"]
            == kv["pages_total"])


def test_chunked_prefill_resume_long_prompts(model):
    """Prompts beyond the largest bucket resume their chunked prefill at
    the first divergent page (page-aligned) — streams identical to the
    cold engine's."""
    rng = _rng(5)
    shared = _toks(rng, 96)
    subs = [(1, np.concatenate([shared, _toks(rng, 70)]), 8),
            (2, np.concatenate([shared, _toks(rng, 81)]), 8)]
    kw = dict(max_len=256, max_slots=2, prompt_buckets=(16, 64))
    got, _ = _serve(_engine(model, prefix_cache=True, **kw), subs)
    want, _ = _serve(_engine(model, prefix_cache=False, **kw), subs)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")


# ------------------------------------------------ pool-pressure drills


def test_pool_exhausted_defers_admission_never_fails(model):
    """A pool sized well below max_slots * per_seq: admissions defer
    with ``serving.kv_pool_exhausted`` backpressure, every request still
    finishes ok, and the streams match the uncontended engine's."""
    rng = _rng(6)
    prompts = [_toks(rng, 10) for _ in range(6)]
    subs = [(i, p, 40) for i, p in enumerate(prompts)]
    tight = _engine(model, max_slots=6, prompt_buckets=(16,),
                    pool_pages=6)
    got, reqs = _serve(tight, subs, serialize_first=False)
    assert all(r.status == "ok" for r in reqs)
    assert resilience.counters().get("serving.kv_pool_exhausted", 0) > 0
    roomy = _engine(model, max_slots=6, prompt_buckets=(16,))
    want, _ = _serve(roomy, subs, serialize_first=False)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")
    # retirement returned every grant
    kv = tight.kv_stats()
    assert kv["pages_free"] + kv["prefix_cached_pages"] \
        == kv["pages_total"]


def test_preemption_resumes_bit_identically(model):
    """Decode growth outrunning the pool preempts the youngest slot
    (``serving.kv_preempted``) instead of failing it; the preempted
    request re-admits through the prefix cache and its final stream is
    bit-identical to the uncontended run."""
    rng = _rng(7)
    # short prompts, long decode: admission fits but growth collides
    prompts = [_toks(rng, 6) for _ in range(4)]
    subs = [(i, p, 60) for i, p in enumerate(prompts)]
    tight = _engine(model, max_slots=4, max_len=96, prompt_buckets=(8,),
                    pool_pages=5)
    got, reqs = _serve(tight, subs, serialize_first=False)
    assert all(r.status == "ok" for r in reqs)
    assert resilience.counters().get("serving.kv_preempted", 0) > 0
    roomy = _engine(model, max_slots=4, max_len=96, prompt_buckets=(8,))
    want, _ = _serve(roomy, subs, serialize_first=False)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")


def test_preempted_fold_past_chunk_width_stays_compiled(model):
    """A preempted request whose folded prompt (orig + emitted) outgrows
    the largest bucket re-admits through the CHUNKED path even on an
    engine whose max_len is NOT a chunk multiple (submit() rejects such
    long prompts, but preemption creates them legitimately): the chunk
    programs must be in the warmed set — zero post-warmup compiles —
    and the streams stay bit-identical to the uncontended run."""
    from paddle_tpu.jit import count_backend_compiles

    rng = _rng(15)
    # max_len 24 is NOT a multiple of chunk_w 16; 10-token prompts with
    # max_new 10 on a 4-page pool admit together under the serial
    # scheduler's one-segment headroom, then COLLIDE on growth — the
    # preempted one folds to a 17+-token prompt
    kw = dict(max_slots=2, max_len=24, page_size=8, prompt_buckets=(16,),
              prefix_cache=False, pipeline=False)
    prompts = [_toks(rng, 10) for _ in range(2)]
    subs = [(i, p, 10) for i, p in enumerate(prompts)]
    tight = _engine(model, pool_pages=4, **kw)
    tight.warmup(segment=4)
    with count_backend_compiles() as compiles:
        got, reqs = _serve(tight, subs, serialize_first=False)
    assert all(r.status == "ok" for r in reqs)
    assert resilience.counters().get("serving.kv_preempted", 0) > 0
    assert compiles == [], \
        f"preempted-fold path compiled {len(compiles)} programs"
    want, _ = _serve(_engine(model, **kw), subs, serialize_first=False)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")


def test_kv_bytes_count_shared_pages_once(model):
    """Physical byte accounting under prefix sharing: slots mapping the
    same cached pages must not report more bytes in use than the pool
    physically holds (grants stay the fragmentation denominator)."""
    rng = _rng(16)
    pre = _toks(rng, 64)                  # 2 shared pages of 32
    eng = _engine(model)
    eng.start(segment=2)
    reqs = [eng.submit(np.concatenate([pre, _toks(rng, 4)]), 30, rid=r)
            for r in (1, 2, 3)]
    eng.step()                            # rid 1 admits, pages cached
    for _ in range(3):
        eng.step()                        # rids 2-3 share the prefix
    kv = eng.kv_stats()
    pool_bytes = (kv["pages_total"] * eng.page_size
                  * kv["bytes_per_token"])
    assert 0 < kv["bytes_in_use"] <= pool_bytes, kv
    assert kv["pages_granted"] <= kv["pages_total"]
    assert 0.0 <= kv["fragmentation_pct"] <= 100.0
    for r in reqs:
        eng.abort(r.rid)
    while eng.has_work():
        eng.step()


def test_engine_fault_bisection_over_dynamic_allocator(model):
    """The PR 3 poison-isolation contract holds on the dynamic pool: the
    poisoned request fails alone, its co-batched peers finish with exact
    tokens, and no page leaks (everything not cache-held returns)."""
    rng = _rng(8)
    prompts = [_toks(rng, 12) for _ in range(4)]
    subs = [(i, p, 8) for i, p in enumerate(prompts)]
    want, _ = _serve(_engine(model), subs, serialize_first=False)
    set_flags({"FLAGS_fault_injection": "serving.engine_fault:1"})
    eng = _engine(model)
    got, reqs = _serve(eng, subs, serialize_first=False)
    statuses = [r.status for r in reqs]
    assert statuses.count("failed") == 1
    assert resilience.counters().get("serving.poison_request", 0) == 1
    for i, r in enumerate(reqs):
        if r.status == "ok":
            np.testing.assert_array_equal(
                np.asarray(r.tokens), want[i], err_msg=f"survivor {i}")
    kv = eng.kv_stats()
    assert kv["pages_free"] + kv["prefix_cached_pages"] \
        == kv["pages_total"]


# --------------------------------------------- compile & config hygiene


def test_zero_compiles_through_allocator_and_prefix_path(model):
    """A warmed engine records ZERO XLA compiles while serving through
    dynamic grants, CoW copies, prefix-resume prefill, and decode growth
    — page-table CONTENTS change, traced shapes don't."""
    from paddle_tpu.jit import count_backend_compiles

    rng = _rng(9)
    pre = _toks(rng, 48)
    eng = _engine(model, max_slots=2, max_len=64,
                  prompt_buckets=(8, 16), page_size=16)
    eng.warmup(segment=3)
    with count_backend_compiles() as compiles:
        subs = [(1, np.concatenate([pre[:16], _toks(rng, 5)]), 6),
                (2, np.concatenate([pre[:16], _toks(rng, 3)]), 6),
                (3, pre[:27].copy(), 6)]     # mid-page CoW
        _, reqs = _serve(eng, subs, segment=3)
    assert all(r.status == "ok" for r in reqs)
    assert eng.kv_stats()["prefix_tokens_saved"] > 0
    assert compiles == [], \
        f"allocator path compiled {len(compiles)} programs"


def test_max_len_round_up_is_surfaced(model):
    """Satellite: the silent page-multiple round-up of ``max_len`` is
    logged and surfaced in ``stats()['kv']``."""
    eng = _engine(model, max_len=100, page_size=32)   # -> 128
    assert eng.max_len == 128
    eng.start(segment=2)
    kv = eng.stats()["kv"]
    assert kv["max_len"] == 128
    assert kv["max_len_rounded_from"] == 100
    clean = _engine(model, max_len=128, page_size=32)
    clean.start(segment=2)
    assert clean.stats()["kv"]["max_len_rounded_from"] is None


def test_pool_must_hold_one_full_sequence(model):
    with pytest.raises(ValueError, match="pool_pages"):
        _engine(model, pool_pages=2)          # < per_seq (128/32 = 4)


# ------------------------------------------------- gauges & frontend


def test_kv_pool_gauges_and_frontend_health(model):
    """The redefined gauges (`serving.kv_pages_free` /
    `serving.kv_pages_total` / `serving.kv_fragmentation_pct` over
    granted pages, `serving.prefix_hit_rate`, per-slot
    `serving.kv_slot_pages{slot=}`) land in the registry, and the
    frontend surfaces pool pressure in ``health()``."""
    rng = _rng(10)
    pre = _toks(rng, 32)
    eng = _engine(model)
    fe = ServingFrontend(eng, max_queue=8, segment=2)
    r1 = fe.submit(np.concatenate([pre, _toks(rng, 6)]),
                   max_new_tokens=12)
    fe.step()
    r2 = fe.submit(np.concatenate([pre, _toks(rng, 4)]),
                   max_new_tokens=12)
    fe.step()
    h = fe.health()
    assert h["kv_pages_total"] == eng.kv_stats()["pages_total"]
    assert 0 <= h["kv_pages_free"] <= h["kv_pages_total"]
    assert "kv_fragmentation_pct" in h and "prefix_hit_rate" in h
    assert h["kv_admission_blocked"] is False
    snap = telemetry.registry().snapshot()
    g = snap["gauges"]
    assert g["serving.kv_pages_total"] == eng.kv_stats()["pages_total"]
    assert "serving.kv_pages_free" in g
    assert "serving.kv_fragmentation_pct" in g
    assert "serving.prefix_hit_rate" in g
    assert any(k.startswith("serving.kv_slot_pages{")
               for k in g), list(g)
    # the second submit shared the first's prefix: the saved-token
    # counter (serving.prefix_tokens_saved) ticked
    assert snap["counters"].get("serving.prefix_tokens_saved", 0) > 0
    res = fe.results(wait=True, timeout=60)
    assert res[r1].status == "ok" and res[r2].status == "ok"
    fe.shutdown(drain=True)


def test_frontend_holds_queue_on_pool_backpressure(model):
    """When the engine defers its queue head on pool exhaustion, the
    frontend stops spilling entries into the engine's FIFO — they wait
    in the frontend's priority queue (`kv_admission_blocked`)."""
    rng = _rng(11)
    eng = _engine(model, max_slots=6, prompt_buckets=(16,), pool_pages=4)
    fe = ServingFrontend(eng, max_queue=16, segment=2)
    rids = [fe.submit(_toks(rng, 12), max_new_tokens=48)
            for _ in range(4)]
    saw_blocked = False
    for _ in range(60):
        fe.step()
        if fe.health()["kv_admission_blocked"]:
            saw_blocked = True
            break
    assert saw_blocked
    # new submits while blocked wait in the FRONTEND's priority queue,
    # not the engine's FIFO
    engine_queued = len(eng.queued_requests())
    late = [fe.submit(_toks(rng, 12), max_new_tokens=48)
            for _ in range(2)]
    fe.step()
    if fe.health()["kv_admission_blocked"]:
        assert len(eng.queued_requests()) <= engine_queued
    res = fe.results(wait=True, timeout=120)
    assert sorted(res) == sorted(rids + late)
    assert all(r.status == "ok" for r in res.values())
    fe.shutdown(drain=True)


def test_obs_kv_renders_live_and_snapshot(model, tmp_path, capsys):
    """`obs kv` renders pool occupancy, fragmentation, prefix hit rate,
    and per-slot page counts from the live registry and from a saved
    snapshot (the `obs slo`/`obs fleet` pattern)."""
    import json

    from paddle_tpu.tools.obs import main as obs_main

    rng = _rng(12)
    pre = _toks(rng, 32)
    eng = _engine(model)
    subs = [(1, np.concatenate([pre, _toks(rng, 5)]), 6),
            (2, np.concatenate([pre, _toks(rng, 3)]), 6)]
    _serve(eng, subs)
    assert obs_main(["kv"]) == 0
    out = capsys.readouterr().out
    assert "pages granted" in out and "prefix" in out
    snap_path = tmp_path / "snap.json"
    snap_path.write_text(json.dumps(telemetry.registry().snapshot()))
    assert obs_main(["kv", str(snap_path)]) == 0
    out = capsys.readouterr().out
    assert "per-slot granted pages" in out
    assert obs_main(["kv", str(tmp_path / "nope.json")]) == 2


def test_kv_pool_summary_from_snapshot(model):
    rng = _rng(13)
    from paddle_tpu.core import perfwatch

    eng = _engine(model)
    _serve(eng, [(1, _toks(rng, 20), 6)])
    s = perfwatch.kv_pool_summary()
    assert s["pages_total"] == eng.kv_stats()["pages_total"]
    s2 = perfwatch.kv_pool_summary(telemetry.registry().snapshot())
    assert s2["pages_total"] == s["pages_total"]
    assert isinstance(s2["slot_pages"], dict)


# ------------------------------------------------------------ TP pools


def test_tp_sharded_dynamic_pool_bit_identity(model):
    """PR 12 contract over the dynamic allocator: a TP engine (degree 1
    mesh — degree > 1 needs multiple devices) over a page pool with
    prefix sharing emits streams bit-identical to the single-chip
    engine."""
    from paddle_tpu.models.tp_serving import TPShardedEngine, serving_mesh

    rng = _rng(14)
    pre = _toks(rng, 32)
    subs = [(1, np.concatenate([pre, _toks(rng, 8)]), 8),
            (2, np.concatenate([pre, _toks(rng, 5)]), 8)]
    mesh = serving_mesh(1)
    tp = TPShardedEngine(model, max_slots=4, max_len=128, page_size=32,
                         prompt_buckets=(16, 32, 64), seed=7, mesh=mesh,
                         pool_pages=12)
    got, reqs = _serve(tp, subs)
    assert all(r.status == "ok" for r in reqs)
    assert tp.kv_stats()["prefix_tokens_saved"] > 0
    want, _ = _serve(_engine(model), subs)
    for i, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_array_equal(g, w, err_msg=f"request {i}")
