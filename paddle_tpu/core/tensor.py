"""Eager Tensor.

The user-facing tensor (analog of the reference's ``paddle::Tensor``,
/root/reference/paddle/phi/api/include/tensor.h:82, with autograd meta as in
eager/autograd_meta.h). It wraps a ``jax.Array`` — which is already a
device-resident, possibly-sharded XLA buffer — so "DenseTensor +
DistTensor" collapse into one type: a Tensor whose value carries a
``NamedSharding`` over a mesh IS the distributed tensor.

stop_gradient defaults to True (reference semantics); ``Parameter`` flips it.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from . import speculation as _spec
from .autograd import AccumulationNode
from .dtype import convert_dtype, to_jax_dtype

__all__ = ["Tensor", "Parameter", "to_tensor", "TracedConcretizationError"]


class TracedConcretizationError(RuntimeError):
    """Raised when eager-only materialization (.numpy()/.item()/bool) is
    attempted on a traced value — the framework's graph-break signal
    (to_static full_graph=False catches it to fall back to eager)."""


def _is_tracer(v) -> bool:
    return isinstance(v, jax.core.Tracer)


class Tensor:
    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_grad_node",
        "_grad_slot",
        "_acc_node",
        "name",
        "persistable",
        "_placements_hint",
        "_partial_info",
        "_lazy_init",
        "__weakref__",
    )

    _id_counter = 0

    def __init__(self, value=None, dtype=None, place=None, stop_gradient=True, name=None):
        if value is None:
            value = jnp.zeros((), dtype=to_jax_dtype(dtype) or jnp.float32)
        elif isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, (jax.Array, jax.core.Tracer)):
            value = jnp.asarray(value, dtype=to_jax_dtype(dtype))
        if dtype is not None and value.dtype != to_jax_dtype(dtype):
            value = value.astype(to_jax_dtype(dtype))
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None
        self._grad_node = None
        self._grad_slot = 0
        self._acc_node = None
        self.name = name or f"tensor_{Tensor._next_id()}"
        self.persistable = False
        self._placements_hint = None
        self._partial_info = None
        self._lazy_init = None

    @classmethod
    def _next_id(cls):
        cls._id_counter += 1
        return cls._id_counter

    @classmethod
    def _from_value(cls, value, stop_gradient=True, name=None):
        t = cls.__new__(cls)
        t._value = value
        t.stop_gradient = stop_gradient
        t._grad = None
        t._grad_node = None
        t._grad_slot = 0
        t._acc_node = None
        t.name = name or f"tensor_{cls._next_id()}"
        t.persistable = False
        t._placements_hint = None
        t._partial_info = None
        t._lazy_init = None
        return t

    # ---------------- autograd plumbing ----------------

    def _grad_edge(self):
        """(node, slot) this tensor's gradient should flow into."""
        if self._grad_node is not None:
            return self._grad_node, self._grad_slot
        if not self.stop_gradient:
            if self._acc_node is None:
                self._acc_node = AccumulationNode(self)
            return self._acc_node, 0
        return None, 0

    def _accumulate_grad(self, value):
        from .selected_rows import SelectedRows

        if getattr(self, "main_grad", False):
            # fp32 gradient accumulation (reference master_grad:
            # fleet/utils/mix_precision_utils.py MixPrecisionLayer._param_hook
            # + the master_grad static pass): upcast each incoming bf16/fp16
            # cotangent BEFORE the += so long micro-batch accumulations keep
            # full mantissa precision — row-sparse grads included (their
            # per-row values accumulate across micro-batches the same way)
            if isinstance(value, SelectedRows):
                if value.dtype != jnp.float32:
                    value = value.astype(jnp.float32)
            elif isinstance(value, Tensor):
                if value._value.dtype != jnp.float32:
                    # .astype is a recorded cast op, so a create_graph
                    # cotangent keeps its graph through the upcast
                    value = value.astype("float32")
            elif value.dtype != jnp.float32:
                value = value.astype(jnp.float32)
        if isinstance(value, SelectedRows):
            # row-sparse grad (sparse embedding): keep sparse while possible
            if self._grad is None:
                self._grad = value
            elif isinstance(self._grad, SelectedRows):
                self._grad = self._grad + value
            else:
                self._grad = Tensor._from_value(
                    self._grad._value + value.to_dense(), stop_gradient=True)
            return
        if isinstance(self._grad, SelectedRows):
            self._grad = Tensor._from_value(
                self._grad.to_dense() + (value._value if isinstance(value, Tensor)
                                         else value), stop_gradient=True)
            return
        if isinstance(value, Tensor):
            # create_graph mode: keep the grad's graph so it can be
            # differentiated again (reference: grad var with grad node)
            self._grad = value if self._grad is None else self._grad + value
        elif self._grad is None:
            self._grad = Tensor._from_value(value, stop_gradient=True, name=self.name + "@GRAD")
        elif self._grad._grad_node is not None:
            # existing grad carries a graph (earlier create_graph backward):
            # rebuild via a recorded add so value and graph stay in sync
            self._grad = self._grad + Tensor._from_value(value, stop_gradient=True)
        else:
            self._grad._value = self._grad._value + value

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        if g is None:
            self._grad = None
        elif isinstance(g, Tensor):
            self._grad = g
        else:
            self._grad = Tensor._from_value(jnp.asarray(g))

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def trainable(self) -> bool:
        """Plain Tensors mirror stop_gradient; Parameter overrides with its
        own slot (so optimizers accept either)."""
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v: bool):
        self.stop_gradient = not bool(v)

    def backward(self, grad_tensor=None, retain_graph=False, create_graph=False):
        autograd.backward([self], [grad_tensor] if grad_tensor is not None else None,
                          retain_graph=retain_graph, create_graph=create_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        return Tensor._from_value(self._value, stop_gradient=True, name=self.name + ".detach")

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import assign

        return assign(self)

    def register_hook(self, hook):
        node, slot = self._grad_edge()
        if isinstance(node, AccumulationNode):
            def wrapped(g):
                from .selected_rows import SelectedRows

                if isinstance(g, SelectedRows):
                    # hooks see the dense view; None keeps the sparse grad
                    new = _unwrap_opt(hook(Tensor._from_value(g.to_dense())))
                    return g if new is None else new
                return _unwrap_opt(hook(Tensor._from_value(g)))

            node.hooks.append(wrapped)
            return
        if node is None:
            raise RuntimeError(
                "register_hook: tensor has no grad edge (stop_gradient "
                "or no recorded op)")

        # non-leaf: hook fires when this tensor's cotangent is computed
        # during backward (reference: hooks on any tensor,
        # paddle/fluid/eager/hooks.h)
        def wrapped_nl(g):
            return _unwrap_opt(hook(Tensor._from_value(g)))

        if node.slot_hooks is None:
            node.slot_hooks = {}
        node.slot_hooks.setdefault(slot, []).append(wrapped_nl)

    # ---------------- metadata ----------------

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return convert_dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        from .place import CPUPlace, TPUPlace

        if _is_tracer(self._value):
            return TPUPlace(0)
        dev = next(iter(self._value.devices()), None)
        if dev is None or dev.platform == "cpu":
            return CPUPlace(0)
        return TPUPlace(dev.id)

    @property
    def T(self):
        from ..ops import transpose

        perm = list(range(self.ndim))[::-1]
        return transpose(self, perm)

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def element_size(self):
        return self._value.dtype.itemsize

    def is_floating_point(self):
        return jnp.issubdtype(self._value.dtype, jnp.floating)

    # ---------------- materialization ----------------

    def numpy(self) -> np.ndarray:
        traced = _is_tracer(self._value)
        if _spec._state.mode is not None:  # SOT-style guarded speculation
            out = _spec.on_concretize(self, traced)
            if out is not None:
                return out
        if traced:
            raise TracedConcretizationError(
                "Cannot call .numpy() inside a traced (to_static) region")
        return np.asarray(self._value)

    def item(self):
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        if self.size != 1:
            raise ValueError("The truth value of a multi-element Tensor is ambiguous")
        return bool(self.item())

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        if _is_tracer(self._value):
            return f"Tensor(shape={self.shape}, dtype={self.dtype.name}, traced, stop_gradient={sg})"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={sg},\n{np.asarray(self._value)})"
        )

    # ---------------- conversion / movement ----------------

    def astype(self, dtype) -> "Tensor":
        from ..ops import cast

        return cast(self, dtype)

    cast = astype

    def to(self, target) -> "Tensor":
        from .place import Place

        if isinstance(target, str) and target in ("cpu", "tpu") or isinstance(target, Place):
            from .place import set_device, current_place

            place = target if isinstance(target, Place) else None
            if place is None:
                from .place import CPUPlace, TPUPlace

                place = CPUPlace(0) if target == "cpu" else TPUPlace(0)
            return Tensor._from_value(
                jax.device_put(self._value, place.jax_device()),
                stop_gradient=self.stop_gradient,
            )
        return self.astype(target)

    def cpu(self):
        return self.to("cpu")

    def tpu(self):
        return self.to("tpu")

    cuda = tpu  # reference-API compatibility spelling

    def pin_memory(self):
        return self

    # ---------------- in-place-style mutation (leaf bookkeeping) ----------------

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value, dtype=self._value.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}"
            )
        # Preserve sharding of the old value when it had one.
        old = self._value
        if isinstance(old, jax.Array) and not _is_tracer(old) and hasattr(old, "sharding"):
            try:
                value = jax.device_put(value, old.sharding)
            except Exception:
                pass
        self._value = value
        return self

    def copy_(self, other):
        return self.set_value(other)

    def fill_(self, v):
        return self.set_value(jnp.full_like(self._value, v))

    def zero_(self):
        return self.set_value(jnp.zeros_like(self._value))

    def scale_(self, scale):
        self._value = self._value * scale
        return self

    def add_(self, other):
        other = other._value if isinstance(other, Tensor) else other
        self._value = self._value + other
        return self

    def subtract_(self, other):
        other = other._value if isinstance(other, Tensor) else other
        self._value = self._value - other
        return self

    def multiply_(self, other):
        other = other._value if isinstance(other, Tensor) else other
        self._value = self._value * other
        return self

    # ---------------- operators (populated by ops module at import) ----------------
    # Methods like reshape/transpose/sum/... are monkey-patched in
    # paddle_tpu/ops/__init__.py, mirroring the reference's math_op_patch.

    def __getitem__(self, idx):
        from ..ops import _getitem

        return _getitem(self, idx)

    def __setitem__(self, idx, value):
        value = value._value if isinstance(value, Tensor) else value
        self._value = self._value.at[idx].set(value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # jax pytree/array protocol helpers
    def __jax_array__(self):
        return self._value


def _unwrap_opt(x):
    if x is None:
        return None
    return x._value if isinstance(x, Tensor) else x


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, persistable=True."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "need_clip", "main_grad")

    def __init__(self, value, name=None, trainable=True):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.need_clip = True
        self.main_grad = False

    @classmethod
    def from_tensor(cls, t: Tensor, name=None, trainable=True):
        p = cls.__new__(cls)
        Tensor.__init__(p, t._value, stop_gradient=not trainable, name=name)
        p.trainable = trainable
        p.persistable = True
        p.optimize_attr = {"learning_rate": 1.0}
        p.regularizer = None
        p.is_distributed = False
        p.need_clip = True
        p.main_grad = False
        return p

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """``paddle.to_tensor`` analog."""
    if isinstance(data, Tensor):
        t = Tensor._from_value(data._value, stop_gradient=stop_gradient)
        if dtype is not None:
            t = t.astype(dtype) if t.dtype != convert_dtype(dtype) else t
        t.stop_gradient = stop_gradient
        return t
    value = jnp.asarray(data, dtype=to_jax_dtype(dtype))
    if place is not None:
        from .place import Place

        if isinstance(place, Place):
            value = jax.device_put(value, place.jax_device())
    return Tensor._from_value(value, stop_gradient=stop_gradient)
