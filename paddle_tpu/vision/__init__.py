"""paddle_tpu.vision — transforms, datasets, model zoo.

Analog of /root/reference/python/paddle/vision/.
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import transforms  # noqa: F401

__all__ = ["datasets", "models", "ops", "transforms"]
