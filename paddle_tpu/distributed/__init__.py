"""paddle_tpu.distributed — distribution over TPU device meshes.

Reference: /root/reference/python/paddle/distributed/ (148K LoC across
fleet/, auto_parallel/, communication/, launch/, checkpoint/). The
TPU-native design (SURVEY.md §7) folds the reference's runtime machinery
into XLA: sharding propagation ← GSPMD (replacing 113 SPMD rule files),
reshard ← compile-time collectives (replacing the reshard function library),
ProcessGroupNCCL ← HLO collectives over ICI/DCN. What remains host-side is
this package: mesh/placement metadata, the collective API surface, hybrid-
parallel layer wrappers, and checkpointing.
"""
from . import auto_parallel  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import checkpoint  # noqa: F401
from . import comm_ops  # noqa: F401
from . import fleet  # noqa: F401
from . import gang  # noqa: F401
from . import io  # noqa: F401
from . import launch  # noqa: F401
from . import ps  # noqa: F401
from . import rpc  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .extras import (  # noqa: F401
    CountFilterEntry,
    DistAttr,
    ParallelMode,
    ProbabilityEntry,
    ReduceType,
    ShardingStage1,
    ShardingStage2,
    ShardingStage3,
    ShowClickEntry,
    alltoall,
    alltoall_single,
    broadcast_object_list,
    gather,
    get_backend,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    is_available,
    scatter_object_list,
    shard_scaler,
    split,
    wait,
)
from .auto_parallel import DistModel, Strategy, to_static  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorruptionError,
    commit_snapshot,
    committed_step,
    latest_complete_snapshot,
    load_latest_snapshot,
    load_state_dict,
    save_snapshot,
    save_state_dict,
)
from .gang import (  # noqa: F401
    GangContext,
    PeerFailureDetector,
    PeerFailureError,
    gang_barrier,
    gang_context,
)
from .spawn import MultiprocessContext, spawn  # noqa: F401
from .api import (  # noqa: F401
    ShardDataloader,
    dtensor_from_fn,
    shard_dataloader,
    reshard,
    shard_constraint,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    to_named_sharding,
    placements_to_spec,
    unshard_dtensor,
)
from .collective import (  # noqa: F401
    CommTimeoutError,
    Group,
    P2POp,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    all_to_all,
    barrier,
    batch_isend_irecv,
    broadcast,
    destroy_process_group,
    get_group,
    get_rank,
    get_world_size,
    init_parallel_env,
    irecv,
    is_initialized,
    isend,
    new_group,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
)
from .parallel import DataParallel, ParallelEnv  # noqa: F401
from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import (  # noqa: F401
    ProcessMesh,
    get_mesh,
    init_mesh,
    set_mesh,
)

__all__ = [
    "ProcessMesh", "get_mesh", "set_mesh", "init_mesh",
    "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "shard_constraint", "dtensor_from_fn",
    "shard_layer", "shard_optimizer", "unshard_dtensor",
    "shard_dataloader", "ShardDataloader",
    "Group", "ReduceOp", "new_group", "get_rank", "get_world_size",
    "init_parallel_env", "is_initialized", "barrier",
    "all_reduce", "all_gather", "broadcast", "reduce", "scatter",
    "all_to_all", "reduce_scatter", "send", "recv", "isend", "irecv",
    "CommTimeoutError",
    "DataParallel", "ParallelEnv", "comm_ops",
    "Strategy", "DistModel", "to_static",
    "spawn", "MultiprocessContext",
    "ParallelMode", "ReduceType", "DistAttr",
    "alltoall", "alltoall_single", "gather",
    "broadcast_object_list", "scatter_object_list",
    "get_backend", "is_available", "wait", "split", "shard_scaler",
    "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "InMemoryDataset", "QueueDataset", "launch", "io",
    "CheckpointCorruptionError", "save_snapshot", "load_latest_snapshot",
    "latest_complete_snapshot", "commit_snapshot", "committed_step",
    "PeerFailureError", "PeerFailureDetector", "GangContext",
    "gang_barrier", "gang_context", "gang",
]
