"""Partial placement semantics + non-leaf tensor hooks (VERDICT r2 item 8).

Reshard matrix {r, s, p} -> {r, s, p} preserving the global value/sum —
the analog of the reference's pairwise reshard functions
(paddle/phi/core/distributed/auto_parallel/reshard/{r,s,p}_to_*) and
test/auto_parallel/reshard_* suite. Non-leaf hooks mirror
paddle/fluid/eager/hooks.h (hooks on any tensor).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import Partial, Replicate, Shard

N = 8


@pytest.fixture
def mesh():
    m = dist.ProcessMesh(np.arange(N), ["dp"])
    dist.set_mesh(m)
    return m


DATA = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)


def _make(kind, mesh):
    t = paddle.to_tensor(DATA.copy())
    if kind == "r":
        return dist.shard_tensor(t, mesh, [Replicate()])
    if kind == "s":
        return dist.shard_tensor(t, mesh, [Shard(0)])
    return dist.shard_tensor(t, mesh, [Partial()])


def _placements(kind):
    return {"r": [Replicate()], "s": [Shard(0)], "p": [Partial()]}[kind]


def _global_value(t, mesh):
    """Resolve to the full value: partial tensors reduce on exit."""
    out = dist.reshard(t, mesh, [Replicate()])
    return np.asarray(out._value)


@pytest.mark.parametrize("src", ["r", "s", "p"])
@pytest.mark.parametrize("dst", ["r", "s", "p"])
def test_reshard_matrix_preserves_global_value(src, dst, mesh):
    t = _make(src, mesh)
    out = dist.reshard(t, mesh, _placements(dst))
    np.testing.assert_allclose(_global_value(out, mesh), DATA)
    if dst == "s" and src != "p":
        assert out._value.addressable_shards[0].data.shape == (2, 8)
    if dst == "p":
        # pending-sum state: stacked contributions, Shard(0) over the axis
        assert out._partial_info is not None
        assert out._value.shape == (N, 16, 8)
        local = out._value.addressable_shards[0].data
        assert local.shape == (1, 16, 8)


def test_partial_sum_semantics(mesh):
    """p→r is an all-reduce of per-device contributions: entering partial
    from a full value keeps the global SUM (r_to_p gives one owner the
    value), and element-wise accumulation into the stacked state reduces
    correctly."""
    t = _make("p", mesh)
    # simulate per-device partial accumulation: add 1 to every contribution
    import jax.numpy as jnp

    t._value = t._value + 1.0  # each of the 8 slots gains 1
    out = dist.reshard(t, mesh, [Replicate()])
    np.testing.assert_allclose(np.asarray(out._value), DATA + 8.0)


def test_partial_to_shard_is_reduce_scatter(mesh):
    t = _make("p", mesh)
    out = dist.reshard(t, mesh, [Shard(0)])
    np.testing.assert_allclose(_global_value(out, mesh), DATA)
    assert out._value.addressable_shards[0].data.shape == (2, 8)
    assert out._partial_info is None


# ---------------------------------------------------------- non-leaf hooks

def test_non_leaf_hook_fires_and_scales():
    x = paddle.to_tensor(np.asarray([2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = x * x            # non-leaf
    seen = []

    def hook(g):
        seen.append(np.asarray(g._value).copy())
        return g * 10.0

    y.register_hook(hook)
    loss = (y * 5.0).sum()
    loss.backward()
    # hook saw dL/dy = 5, and scaled it by 10 before backprop through x*x
    np.testing.assert_allclose(seen[0], [5.0, 5.0])
    np.testing.assert_allclose(np.asarray(x._grad._value),
                               10.0 * 5.0 * 2.0 * np.asarray([2.0, 3.0]))


def test_non_leaf_hook_observe_only():
    x = paddle.to_tensor(np.asarray([1.0, 4.0], np.float32),
                         stop_gradient=False)
    h = x * 2.0
    seen = []
    h.register_hook(lambda g: seen.append(np.asarray(g._value).copy()))
    (h ** 2).sum().backward()
    # dL/dh = 2h = [4, 16]; observe-only hook (returns None) changes nothing
    np.testing.assert_allclose(seen[0], [4.0, 16.0])
    np.testing.assert_allclose(np.asarray(x._grad._value), [8.0, 32.0])


def test_non_leaf_hook_on_intermediate_activation():
    import paddle_tpu.nn as nn

    paddle.seed(0)
    lin1 = nn.Linear(4, 8)
    lin2 = nn.Linear(8, 2)
    x = paddle.to_tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32))

    def run(scale):
        for lay in (lin1, lin2):
            for p in lay.parameters():
                p.clear_grad()
        h = lin1(x)
        if scale is not None:
            h.register_hook(lambda g: g * scale)
        (lin2(h) ** 2).mean().backward()
        return np.asarray(lin1.weight._grad._value).copy()

    base = run(None)
    doubled = run(2.0)
    np.testing.assert_allclose(doubled, 2 * base, rtol=1e-6)


def test_partial_source_non_partial_shard_tensor(mesh):
    """shard_tensor (the public entry) on a partial source with a
    non-partial target must resolve the pending sum, never lay out the
    stacked internal representation."""
    t = _make("p", mesh)
    out = dist.shard_tensor(t, mesh, [Replicate()])
    assert out.shape == [16, 8]
    np.testing.assert_allclose(np.asarray(out._value), DATA)
    out_s = dist.shard_tensor(_make("p", mesh), mesh, [Shard(0)])
    assert out_s._value.addressable_shards[0].data.shape == (2, 8)


def test_partial_entry_rejects_autograd(mesh):
    t = paddle.to_tensor(DATA.copy(), stop_gradient=False)
    with pytest.raises(NotImplementedError, match="autograd"):
        dist.shard_tensor(t, mesh, [Partial()])
