"""tpu-lint fixture: pallas_call kernels wrapped through
functools.partial — the direct-argument form and the local-binding
form must both register the kernel body as a jit entry, while params
bound BY the partial are static (branching on them is fine).
NOT importable production code — the analyzer only parses it."""
import functools
import time

from jax.experimental import pallas as pl


def _direct_kernel(x_ref, o_ref, *, causal):
    if causal:                      # static (partial-bound): no finding
        o_ref[...] = x_ref[...]
    t = time.time()                 # tracer-wall-clock
    if x_ref[0] > t:                # tracer-host-branch
        o_ref[...] = x_ref[...] * 2.0


def _bound_kernel(s_ref, x_ref, o_ref, *, page_size):
    if page_size > 8:               # static (partial-bound): no finding
        o_ref[...] = x_ref[...]
    o_ref[...] = x_ref.item()       # tracer-concretize


def run_direct(x):
    # partial in the ARGUMENT position of the wrap call
    return pl.pallas_call(
        functools.partial(_direct_kernel, causal=True),
        out_shape=x)(x)


def run_bound(x, s):
    # local partial binding, then the wrap call by name
    kernel = functools.partial(_bound_kernel, page_size=16)
    return pl.pallas_call(kernel, grid=(1,))(s, x)
