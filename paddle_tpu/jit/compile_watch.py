"""Compile watchdog: production monitoring of XLA backend compiles.

PR 5's serving contract is ZERO post-warmup XLA compiles — a single
recompile on the hot path costs more wall time than thousands of decode
segments, and until now the invariant was asserted by exactly one
test-local listener (``tests/test_serving_pipeline.py``) and never
monitored in production. This module promotes that listener into the jit
layer:

* :class:`CompileWatchdog` (one per process, ``compile_watchdog()``)
  registers a ``jax._src.monitoring`` duration listener for
  ``/jax/core/compile/backend_compile_duration`` and counts every
  backend compile into ``xla.compiles_total{phase=...}``:

  - ``warmup`` — inside a :meth:`warmup_scope` (the engine's AOT
    ``warmup()``), or any compile before the first warmup completed
    (model build, program construction);
  - ``serving`` — inside a :meth:`dispatch_context` (the engine wraps
    every non-AOT program dispatch in one) AFTER warmup armed the
    watchdog: a POST-WARMUP RECOMPILE, the invariant violation. The
    event also lands in the flight recorder and triggers a post-mortem
    dump NAMING the recompiled program and its traced shapes (the
    listener itself only learns "a compile happened" from jax — the
    dispatch context carries the who);
  - ``other`` — armed, but outside any serving dispatch (a training
    step compiling in the same process is not a serving regression).

* :func:`count_backend_compiles` — the shared test/bench utility (the
  promoted form of the inline listener): a context manager yielding the
  list of compile durations observed in its scope.

The listener is passive and cheap (one string compare per jax event);
counting/dumping is additionally gated on ``FLAGS_telemetry``.
"""
from __future__ import annotations

import contextlib
import threading

from ..core import telemetry

__all__ = ["CompileWatchdog", "compile_watchdog",
           "count_backend_compiles", "BACKEND_COMPILE_EVENT"]

BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_M_COMPILES = telemetry.counter(
    "xla.compiles_total", "XLA backend compiles by phase: warmup (AOT "
    "warmup scopes + pre-warmup build), serving (a POST-WARMUP RECOMPILE "
    "on the engine dispatch path — dumps the flight recorder naming the "
    "program), other (armed process, non-serving compile)")


def _monitoring():
    from jax._src import monitoring

    return monitoring


class CompileWatchdog:
    """Process-wide compile counter + post-warmup recompile alarm."""

    def __init__(self):
        self._lock = threading.Lock()
        self._registered = False
        self._armed = False          # a warmup completed: serving began
        self._local = threading.local()

    # ----------------------------------------------------------- lifecycle

    def start(self):
        """Register the jax monitoring listener (idempotent)."""
        with self._lock:
            if self._registered:
                return self
            _monitoring().register_event_duration_secs_listener(
                self._on_event)
            self._registered = True
        return self

    def stop(self):
        """Unregister (tests); counters keep their values."""
        with self._lock:
            if not self._registered:
                return
            with contextlib.suppress(Exception):
                _monitoring()._unregister_event_duration_listener_by_callback(
                    self._on_event)
            self._registered = False

    def reset(self):
        """Disarm (tests): compiles count as ``warmup`` again until the
        next :meth:`arm`. Counter values are cleared by
        ``telemetry.reset_telemetry()``, not here."""
        self._armed = False

    def arm(self):
        """Warmup is done: from now on a compile inside a serving
        dispatch context is a recompile incident."""
        self._armed = True

    @property
    def armed(self) -> bool:
        return self._armed

    # ------------------------------------------------------------- scopes

    @contextlib.contextmanager
    def warmup_scope(self):
        """Compiles inside count as ``phase="warmup"`` even when the
        watchdog is armed (a ``scale_out`` replica warming while the
        fleet serves is not an incident)."""
        depth = getattr(self._local, "warm", 0)
        self._local.warm = depth + 1
        try:
            yield
        finally:
            self._local.warm = depth

    @contextlib.contextmanager
    def dispatch_context(self, program, **detail):
        """Names the serving program being dispatched on this thread so
        a compile fired inside can be attributed — the engine wraps its
        non-AOT dispatches (``program`` is the executable-cache key,
        ``detail`` carries the traced shapes)."""
        prev = getattr(self._local, "ctx", None)
        self._local.ctx = {"program": str(program), **detail}
        try:
            yield
        finally:
            self._local.ctx = prev

    # ------------------------------------------------------------ listener

    def _on_event(self, event, duration, **kw):
        if event != BACKEND_COMPILE_EVENT or not telemetry.enabled():
            return
        if getattr(self._local, "warm", 0) > 0 or not self._armed:
            _M_COMPILES.inc(phase="warmup")
            return
        ctx = getattr(self._local, "ctx", None)
        if ctx is None:
            _M_COMPILES.inc(phase="other")
            return
        _M_COMPILES.inc(phase="serving")
        # a post-warmup recompile is a post-mortem moment: the program
        # name + traced shapes are exactly what the operator needs to
        # add the missing bucket/width/segment to warmup()
        telemetry.flight_dump("recompile", seconds=round(duration, 4),
                              **ctx)


_watchdog = CompileWatchdog()


def compile_watchdog() -> CompileWatchdog:
    return _watchdog


@contextlib.contextmanager
def count_backend_compiles():
    """Yield a list that accumulates the duration of every XLA backend
    compile observed in the scope — the one listener implementation
    tests and benches share (``assert not compiles`` is the zero-compile
    invariant)."""
    events = []

    def listener(event, duration, **kw):
        if event == BACKEND_COMPILE_EVENT:
            events.append(duration)

    mon = _monitoring()
    mon.register_event_duration_secs_listener(listener)
    try:
        yield events
    finally:
        with contextlib.suppress(Exception):
            mon._unregister_event_duration_listener_by_callback(listener)
