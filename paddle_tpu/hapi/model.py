"""hapi.Model — the Keras-like trainer.

Analog of /root/reference/python/paddle/hapi/model.py:1472 (``Model`` with
prepare/fit/evaluate/predict/save/load) and callbacks.py (ProgBarLogger,
ModelCheckpoint). The dygraph engine below runs eager; pass
``compiled=True`` to prepare() to train through the whole-step compiled
path (paddle_tpu.jit.TrainStep) — the TPU-native equivalent of the
reference's ``Model`` + ``to_static``.

Fault-tolerant training (reference fleet elastic resume + TPU preemption
discipline): ``fit(checkpoint_dir=..., checkpoint_freq=N)`` saves a
step-numbered snapshot (network + optimizer + GradScaler + epoch/step +
framework RNG state) through the crash-safe
``distributed.checkpoint.save_snapshot`` every N steps;
``fit(resume=True, checkpoint_dir=...)`` restores the newest complete
snapshot and continues mid-epoch — the epoch's shuffle is replayed from
the recorded epoch-start RNG state, already-trained batches are skipped,
then the live RNG stream is restored, so a killed-and-resumed run
reproduces an uninterrupted one step for step. While training, SIGTERM
checkpoints once at the next batch boundary and exits (preemption
notice → graceful handoff); the deterministic fault site ``fit.preempt``
(``FLAGS_fault_injection="fit.preempt:1"``) simulates the kill.
"""
from __future__ import annotations

import json
import os
import signal
import time

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Model", "Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping", "LRSchedulerCallback"]


class Callback:
    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=10, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.monotonic()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items())
            print(f"epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items())
            print(f"epoch {epoch} done in {time.monotonic()-self.t0:.1f}s: "
                  f"{items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))


class Model:
    """Reference hapi/model.py:1472."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._train_step = None
        self._compiled = False
        self._scaler = None

    # ------------------------------------------------ setup

    def prepare(self, optimizer=None, loss=None, metrics=None, compiled=False,
                scaler=None):
        """``scaler``: an ``amp.GradScaler`` — eager ``train_batch`` then
        runs the scale → backward → scaler.step (skip on non-finite) →
        scaler.update recipe, and fit()'s snapshots carry the scaler's
        dynamic-scaling state."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]
        self._compiled = compiled
        self._scaler = scaler
        if scaler is not None and compiled:
            raise ValueError(
                "prepare(scaler=...) is eager-only: the compiled TrainStep "
                "path fuses its own update and does not consult a "
                "GradScaler")
        return self

    # ------------------------------------------------ steps

    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if self._compiled:
            if self._train_step is None:
                from ..jit import TrainStep

                def loss_fn(*outs_and_labels):
                    *outs, lab = outs_and_labels
                    return self._loss(
                        outs[0] if len(outs) == 1 else tuple(outs), lab)

                self._train_step = TrainStep(self.network, loss_fn,
                                             self._optimizer)
            if labels is None:
                raise ValueError(
                    "compiled train_batch requires labels (the loss was "
                    "configured in prepare())")
            loss = self._train_step(*inputs, labels=labels)
            return {"loss": float(loss)}
        out = self.network(*inputs)
        loss = self._loss(out, labels) if self._loss else out
        if self._scaler is not None:
            self._scaler.scale(loss).backward()
            self._scaler.step(self._optimizer)
            self._scaler.update()
        else:
            loss.backward()
            self._optimizer.step()
        self._optimizer.clear_grad()
        logs = {"loss": float(loss)}
        for m in self._metrics:
            m.update(m.compute(out, labels))
        return logs

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core import autograd

        with autograd.no_grad():
            out = self.network(*inputs)
            logs = {}
            if self._loss is not None and labels is not None:
                logs["loss"] = float(self._loss(out, labels))
        for m in self._metrics:
            m.update(m.compute(out, labels))
        return logs

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core import autograd

        with autograd.no_grad():
            return self.network(*inputs)

    # ------------------------------------------------ loops

    @staticmethod
    def _split(batch):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), batch[-1]
        return [batch], None

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            shuffle=True, callbacks=None, num_workers=0,
            resume=False, checkpoint_dir=None, checkpoint_freq=None,
            keep_checkpoints=3, elastic=False):
        """Train. Fault-tolerance knobs:

        * ``checkpoint_dir``: save step-numbered training snapshots here
          (crash-safe, checksummed); also arms the SIGTERM
          checkpoint-once-then-exit handler and the ``fit.preempt``
          fault site.
        * ``checkpoint_freq``: snapshot every N global steps (default:
          end of every epoch).
        * ``resume=True``: restore the newest complete snapshot from
          ``checkpoint_dir`` (no-op when none exists) and continue from
          the exact epoch/step — mid-epoch included.
        * ``keep_checkpoints``: prune to the newest K complete snapshots.
        * ``elastic=True``: gang-recovery mode for supervised
          multi-process runs (``distributed.launch``); requires a
          ``checkpoint_dir`` SHARED by all ranks (snapshots use the
          gang shard layout — one directory, per-gang-rank files). A
          ``PeerFailureDetector`` heartbeats the supervisor's gang store
          and is checked at every batch boundary (and by blocked
          collectives); a dead peer raises ``PeerFailureError`` within
          one heartbeat lease, whereupon fit reuses the SIGTERM
          checkpoint-once path and exits 143 so the supervisor restarts
          the gang at a bumped generation. Periodic snapshots run the
          coordinated commit protocol (``committed_step`` published to
          the gang store) and ``resume=True`` resolves the
          cluster-agreed step so every rank restarts at the same global
          step. The ``elastic.peer_dead`` fault site drills the whole
          path deterministically.
        """
        from ..core import random as framework_random
        from ..core.health import get_health_monitor
        from ..core.resilience import InjectedFault, PeerFailureError, inject
        from ..distributed import gang as gang_mod
        from ..io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            train_data = DataLoader(train_data, batch_size=batch_size,
                                    shuffle=shuffle, num_workers=num_workers)
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(ProgBarLogger(log_freq, verbose))
        if save_dir:
            cbs.append(ModelCheckpoint(save_freq, save_dir))
        for cb in cbs:
            cb.set_model(self)
        monitor = get_health_monitor()

        if resume and not checkpoint_dir:
            raise ValueError("fit(resume=True) requires checkpoint_dir=")
        if elastic and not checkpoint_dir:
            raise ValueError("fit(elastic=True) requires checkpoint_dir=")

        detector, prev_detector = None, None
        if elastic:
            ctx = gang_mod.gang_context()
            if ctx is not None:
                detector = gang_mod.PeerFailureDetector(ctx).start()
                prev_detector = gang_mod.set_active_detector(detector)

        start_epoch, skip_steps, global_step = 0, 0, 0
        resume_epoch_rng = None
        if resume:
            try:
                restored = self._restore_training_snapshot(
                    checkpoint_dir, coordinated=elastic)
            except BaseException:
                # the detector is already installed process-wide but the
                # cleanup try/finally hasn't started: don't leak the
                # heartbeat thread (or a stale global detector) on a
                # failed restore
                if detector is not None:
                    gang_mod.set_active_detector(prev_detector)
                    detector.stop()
                raise
            if restored is not None:
                start_epoch, skip_steps, global_step, resume_epoch_rng = \
                    restored

        # Preemption notice → checkpoint once at the next batch boundary,
        # then exit. Handler installation only works in the main thread;
        # elsewhere (fit inside a worker thread) it is skipped.
        preempt = {"signaled": False}
        prev_handler, handler_installed = None, False
        if checkpoint_dir:
            def _on_sigterm(signum, frame):
                preempt["signaled"] = True
            try:
                prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
                handler_installed = True
            except ValueError:  # not the main thread
                pass

        def _snapshot(epoch, step_in_epoch, epoch_rng, emergency=False):
            # periodic elastic snapshots run the coordinated commit (all
            # ranks barrier, rank 0 publishes committed_step); emergency
            # ones (preemption, peer death) save FIRST, then attempt the
            # commit with a short budget. The step-keyed barrier name
            # makes this deliberately conservative: it publishes only
            # when every rank saved the SAME step (a step-aligned
            # whole-pod preemption), and fails fast otherwise — skewed
            # ranks or a dead peer leave the step uncommitted debris
            # below the last agreed step, never a wrong agreement
            path = self._save_training_snapshot(
                checkpoint_dir, epoch, step_in_epoch, global_step,
                epoch_rng, keep=keep_checkpoints,
                coordinated=elastic and not emergency,
                gang_layout=elastic)
            if elastic and emergency:
                import contextlib

                from ..core.resilience import PeerFailureError as _PFE
                from ..distributed import checkpoint as dckpt

                with contextlib.suppress(_PFE):
                    dckpt.commit_snapshot(
                        checkpoint_dir, global_step,
                        timeout=(2 * detector.lease if detector is not None
                                 else 5.0),
                        detector=detector,
                        # fresh barrier name: a retry on the periodic
                        # name would count its own earlier arrival and
                        # publish a snapshot the dead peer never wrote
                        barrier_name=f"ckpt_emergency/{int(global_step)}")
            return path

        # last completed batch boundary (epoch, next step, epoch RNG) —
        # where the PeerFailureError handler checkpoints from
        cursor = None
        history = []
        try:
            for cb in cbs:
                cb.on_train_begin()
            for epoch in range(start_epoch, epochs):
                for m in self._metrics:
                    m.reset()
                for cb in cbs:
                    cb.on_epoch_begin(epoch)
                logs = {}
                if epoch == start_epoch and skip_steps:
                    # mid-epoch resume: replay this epoch's shuffle from
                    # the recorded epoch-start RNG, fast-forward past the
                    # already-trained batches, then restore the live RNG
                    # stream (dropout etc. continue where they stopped).
                    # DataLoader.iter_from skips at the SAMPLER level —
                    # identical RNG consumption, no wasted dataset[i]
                    # loads — and raises when the epoch no longer has
                    # skip_steps batches (changed batch_size/dataset).
                    live_rng = framework_random.get_rng_state()
                    framework_random.set_rng_state(
                        tuple(resume_epoch_rng))
                    epoch_rng = tuple(resume_epoch_rng)
                    if hasattr(train_data, "iter_from"):
                        data_iter = train_data.iter_from(skip_steps)
                    else:
                        data_iter = iter(train_data)
                        for done in range(skip_steps):
                            try:
                                next(data_iter)
                            except StopIteration:
                                raise ValueError(
                                    f"resume: cannot skip {skip_steps} "
                                    f"batches, the epoch ended after "
                                    f"{done} — data pipeline changed "
                                    "since the checkpoint?") from None
                    framework_random.set_rng_state(live_rng)
                    first_step = skip_steps
                    skip_steps = 0
                else:
                    epoch_rng = framework_random.get_rng_state()
                    data_iter = iter(train_data)
                    first_step = 0
                cursor = (epoch, first_step, epoch_rng)
                for step, batch in enumerate(data_iter, start=first_step):
                    ins, lab = self._split(batch)
                    logs = self.train_batch(ins, lab)
                    global_step += 1
                    cursor = (epoch, step + 1, epoch_rng)
                    monitor.record_loss(logs.get("loss"), step=global_step)
                    for m in self._metrics:
                        logs[_name(m)] = _scalar(m.accumulate())
                    for cb in cbs:
                        cb.on_train_batch_end(step, logs)
                    if elastic:
                        # one lease after a peer dies this raises
                        # PeerFailureError -> checkpoint-once -> exit 143
                        gang_mod.check_peers(f"train step {global_step}")
                    if checkpoint_dir:
                        if preempt["signaled"]:
                            _snapshot(epoch, step + 1, epoch_rng,
                                      emergency=True)
                            raise SystemExit(143)  # 128 + SIGTERM
                        try:
                            inject("fit.preempt")
                        except InjectedFault:
                            # simulated preemption: same
                            # checkpoint-once-then-die path as SIGTERM
                            _snapshot(epoch, step + 1, epoch_rng,
                                      emergency=True)
                            raise
                        if (checkpoint_freq
                                and global_step % checkpoint_freq == 0):
                            _snapshot(epoch, step + 1, epoch_rng)
                if eval_data is not None and (epoch + 1) % eval_freq == 0:
                    logs.update(self.evaluate(eval_data,
                                              batch_size=batch_size,
                                              verbose=0))
                for cb in cbs:
                    cb.on_epoch_end(epoch, logs)
                history.append(logs)
                if checkpoint_dir and not checkpoint_freq:
                    # default cadence: one snapshot per epoch, positioned
                    # at the NEXT epoch's start
                    _snapshot(epoch + 1, 0, framework_random.get_rng_state())
                if any(getattr(cb, "stop_training", False) for cb in cbs):
                    break
            for cb in cbs:
                cb.on_train_end()
        except PeerFailureError as e:
            if not elastic:
                raise
            # gang broken: reuse the SIGTERM checkpoint-once path and
            # exit 143 — the launch() supervisor classifies that as
            # "preempted (checkpointed)" and restarts the gang at a
            # bumped generation, which resumes from the cluster-agreed
            # committed step
            from ..core.resilience import bump_counter, logger as _rlog

            bump_counter("gang.elastic_exit")
            _rlog.warning("peer failure during training (%s); "
                          "checkpointing once and exiting 143 for "
                          "supervised restart", e)
            if cursor is not None:
                _snapshot(*cursor, emergency=True)
            raise SystemExit(143) from e
        finally:
            if detector is not None:
                gang_mod.set_active_detector(prev_detector)
                detector.stop()
            if handler_installed:
                import contextlib

                with contextlib.suppress(ValueError):
                    signal.signal(signal.SIGTERM,
                                  prev_handler or signal.SIG_DFL)
        return history

    # ------------------------------------- training snapshots (auto-resume)

    def _training_state_arrays(self):
        """Flat array state for a snapshot: ``net.*`` (live Parameters —
        loading fills them in place) + ``opt.*`` (accumulators / master
        weights)."""
        arrays = {f"net.{k}": v for k, v in self.network.state_dict().items()}
        if self._optimizer is not None:
            for k, v in self._optimizer.state_dict().items():
                if isinstance(v, Tensor):
                    arrays[f"opt.{k}"] = v
        return arrays

    def _save_training_snapshot(self, checkpoint_dir, epoch, step_in_epoch,
                                global_step, epoch_rng, keep=None,
                                coordinated=False, gang_layout=False):
        """One crash-safe snapshot at ``global_step``: sharded arrays via
        ``distributed.checkpoint.save_snapshot`` + a ``trainer_state.json``
        (epoch/step cursor, RNG states, optimizer step count, GradScaler
        and LR-scheduler state). The json lands BEFORE the shard commit
        marker, so a snapshot is readable iff it is complete. With
        ``coordinated``, the gang's commit barrier runs after the shards
        land and rank 0 publishes the cluster-agreed step."""
        from ..core import random as framework_random
        from ..distributed import checkpoint as dckpt
        from ..optimizer.lr import LRScheduler

        opt = self._optimizer
        lr_state = None
        if opt is not None and isinstance(opt._learning_rate, LRScheduler):
            lr_state = opt._learning_rate.state_dict()
        trainer = {
            "epoch": int(epoch),
            "step_in_epoch": int(step_in_epoch),
            "global_step": int(global_step),
            "rng": list(framework_random.get_rng_state()),
            "rng_epoch_start": list(epoch_rng),
            "opt_step_count": int(opt._step_count) if opt is not None else 0,
            "scaler": (self._scaler.state_dict()
                       if self._scaler is not None else None),
            "lr_sched": lr_state,
        }
        path = os.path.join(checkpoint_dir, f"step_{int(global_step):08d}")
        os.makedirs(path, exist_ok=True)
        dckpt._atomic_json(trainer,
                           os.path.join(path, "trainer_state.json"))
        dckpt.save_snapshot(self._training_state_arrays(), checkpoint_dir,
                            global_step, keep=keep, coordinated=coordinated,
                            gang_layout=gang_layout)
        return path

    def _restore_training_snapshot(self, checkpoint_dir, coordinated=False):
        """Load the newest complete snapshot into the live network,
        optimizer, scaler, LR scheduler, and framework RNG (with
        ``coordinated``, the cluster-agreed committed step instead of
        this host's newest-complete view). Returns
        ``(epoch, step_in_epoch, global_step, epoch_start_rng)`` or None
        when no snapshot exists yet (fresh start)."""
        from ..core import random as framework_random
        from ..distributed import checkpoint as dckpt
        from ..optimizer.lr import LRScheduler

        newest = dckpt.latest_complete_snapshot(checkpoint_dir,
                                                coordinated=coordinated)
        if newest is None:
            return None
        saved_keys = set(dckpt._merged_metadata(newest))
        opt = self._optimizer
        target, opt_target = {}, {}
        for k, v in self.network.state_dict().items():
            if f"net.{k}" in saved_keys:
                target[f"net.{k}"] = v
        if opt is not None:
            # materialize accumulator slots so the checkpoint has live
            # targets to fill (they are otherwise created lazily at the
            # first step); pre-created zeros match a fresh run's init
            opt._ensure_state(opt._parameter_list)
            for k, v in opt.state_dict().items():
                if isinstance(v, Tensor) and f"opt.{k}" in saved_keys:
                    opt_target[f"opt.{k}"] = v
            target.update(opt_target)
        path = dckpt.load_latest_snapshot(target, checkpoint_dir,
                                          coordinated=coordinated)
        if opt_target:
            opt.set_state_dict(
                {k[len("opt."):]: v for k, v in opt_target.items()})
        with open(os.path.join(path, "trainer_state.json")) as f:
            trainer = json.load(f)
        if opt is not None:
            opt._step_count = int(trainer.get("opt_step_count", 0))
            if (trainer.get("lr_sched")
                    and isinstance(opt._learning_rate, LRScheduler)):
                opt._learning_rate.set_state_dict(trainer["lr_sched"])
        if self._scaler is not None and trainer.get("scaler"):
            self._scaler.load_state_dict(trainer["scaler"])
        framework_random.set_rng_state(tuple(trainer["rng"]))
        return (int(trainer["epoch"]), int(trainer["step_in_epoch"]),
                int(trainer["global_step"]),
                tuple(trainer["rng_epoch_start"]))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        from ..io import DataLoader, Dataset

        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size,
                                   num_workers=num_workers)
        cbs = list(callbacks or [])
        for cb in cbs:
            cb.set_model(self)
            cb.on_eval_begin()
        for m in self._metrics:
            m.reset()
        logs = {}
        losses = []
        for step, batch in enumerate(eval_data):
            ins, lab = self._split(batch)
            out = self.eval_batch(ins, lab)
            if "loss" in out:
                losses.append(out["loss"])
            for cb in cbs:
                cb.on_eval_batch_end(step, out)
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs["eval_" + _name(m)] = _scalar(m.accumulate())
        for cb in cbs:
            cb.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        from ..io import DataLoader, Dataset

        if isinstance(test_data, Dataset):
            test_data = DataLoader(test_data, batch_size=batch_size,
                                   num_workers=num_workers)
        outputs = []
        for batch in test_data:
            ins, _ = self._split(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            import jax.numpy as jnp

            outputs = Tensor(jnp.concatenate(
                [o._value for o in outputs], axis=0))
        return outputs

    # ------------------------------------------------ persistence

    def save(self, path, training=True):
        from ..framework.io import save

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load

        self.network.set_state_dict(load(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary

        return summary(self.network, input_size)


def _name(m):
    n = m.name()
    return n[0] if isinstance(n, (list, tuple)) else n


def _scalar(v):
    return float(v[0]) if isinstance(v, (list, tuple)) else float(v)


class EarlyStopping(Callback):
    """Stop fit() when a monitored metric stops improving (reference
    hapi/callbacks.py EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.stop_training = False

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        self._check(logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs or {}, epoch)

    def _check(self, logs, epoch=0):
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.stop_training = True
                if self.verbose:
                    print(f"early stopping at epoch {epoch} "
                          f"({self.monitor}={cur:.5f} best={self.best:.5f})")


class LRSchedulerCallback(Callback):
    """Step the optimizer's LR scheduler (reference callbacks.LRScheduler)."""

    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()
