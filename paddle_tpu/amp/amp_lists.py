"""AMP op lists — which ops run in low precision under O1.

Analog of /root/reference/python/paddle/amp/amp_lists.py
(white_list/black_list/gray_list) and the C++ eager AMP hooks
(paddle/fluid/eager/amp_auto_cast.h). Names refer to this repo's
ops.yaml registry.

* WHITE: matmul-class ops — the MXU work; always worth bf16/fp16.
* BLACK: numerically fragile reductions/exponentials — keep fp32.
* everything else (gray): runs in whatever dtype its inputs arrived in.
"""

WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "conv1d", "conv2d", "conv3d",
    "conv2d_transpose", "einsum", "linear", "addmm",
    "scaled_dot_product_attention",
}

BLACK_LIST = {
    "exp", "expm1", "log", "log2", "log10", "log1p", "pow", "square",
    "sqrt", "rsqrt", "reciprocal", "cosh", "sinh", "erfinv",
    "sum", "mean", "prod", "logsumexp", "norm", "p_norm", "dist",
    "softmax", "log_softmax", "softmax_with_cross_entropy",
    "cross_entropy", "nll_loss", "kl_div", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "smooth_l1_loss",
    "sigmoid_cross_entropy_with_logits",
    "layer_norm", "rms_norm", "group_norm", "instance_norm", "batch_norm",
    "cumsum", "cumprod", "var", "std",
}


def white_list(custom_white=None, custom_black=None):
    w = set(WHITE_LIST)
    if custom_white:
        w |= set(custom_white)
    if custom_black:
        w -= set(custom_black)
    return w


def black_list(custom_black=None, custom_white=None):
    b = set(BLACK_LIST)
    if custom_black:
        b |= set(custom_black)
    if custom_white:
        b -= set(custom_white)
    return b
