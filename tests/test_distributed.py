"""Distributed core: ProcessMesh / placements / shard_tensor / reshard /
comm_ops, on the 8-virtual-CPU-device mesh (conftest.py).

Mirrors the reference's reshard matrix tests
(/root/reference/test/auto_parallel/reshard_{r,s,p}_to_*.py) and
semi_auto_parallel_for_matmul.py, adapted to single-controller jax.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import Partial, Replicate, Shard


@pytest.fixture
def mesh2d():
    return dist.ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])


def test_mesh_metadata(mesh2d):
    assert mesh2d.shape == [4, 2]
    assert mesh2d.dim_names == ["dp", "mp"]
    assert mesh2d.process_ids == list(range(8))
    assert mesh2d.get_dim_size("mp") == 2
    assert 5 in mesh2d
    jm = mesh2d.jax_mesh()
    assert jm.axis_names == ("dp", "mp")


def test_placements_to_spec(mesh2d):
    spec = dist.placements_to_spec([Shard(0), Shard(1)], mesh2d)
    assert spec == jax.sharding.PartitionSpec("dp", "mp")
    spec = dist.placements_to_spec([Replicate(), Shard(0)], mesh2d)
    assert spec == jax.sharding.PartitionSpec("mp")
    spec = dist.placements_to_spec([Replicate(), Replicate()], mesh2d)
    assert spec == jax.sharding.PartitionSpec()
    # both mesh dims on one tensor dim
    spec = dist.placements_to_spec([Shard(1), Shard(1)], mesh2d)
    assert spec == jax.sharding.PartitionSpec(None, ("dp", "mp"))


def test_shard_tensor_layout(mesh2d):
    x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
    xs = dist.shard_tensor(x, mesh2d, [Shard(0), Shard(1)])
    shards = xs._value.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape == (2, 4)
    np.testing.assert_allclose(np.asarray(xs._value), np.asarray(x._value))
    mesh, placements = xs._placements_hint
    assert placements == [Shard(0), Shard(1)]


def test_shard_tensor_divisibility_error(mesh2d):
    x = paddle.to_tensor(np.zeros((6, 8), np.float32))
    with pytest.raises(ValueError):
        dist.shard_tensor(x, mesh2d, [Shard(0)])  # 6 % 4 != 0


def test_reshard_s_to_r(mesh2d):
    x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    xs = dist.shard_tensor(x, mesh2d, [Shard(0)])
    xr = dist.reshard(xs, mesh2d, [Replicate(), Replicate()])
    shards = xr._value.addressable_shards
    assert shards[0].data.shape == (8, 8)
    np.testing.assert_allclose(np.asarray(xr._value), np.asarray(x._value))


def test_reshard_s_to_s(mesh2d):
    x = paddle.to_tensor(np.random.rand(8, 8).astype(np.float32))
    xs = dist.shard_tensor(x, mesh2d, [Shard(0)])
    xt = dist.reshard(xs, mesh2d, [Shard(1)])
    assert xt._value.addressable_shards[0].data.shape == (8, 2)


def test_sharded_matmul_executes(mesh2d):
    """Sharded operands flow through eager ops; XLA handles the layouts."""
    a = dist.shard_tensor(
        paddle.to_tensor(np.random.rand(8, 16).astype(np.float32)),
        mesh2d, [Shard(0), Replicate()])
    b = dist.shard_tensor(
        paddle.to_tensor(np.random.rand(16, 8).astype(np.float32)),
        mesh2d, [Replicate(), Shard(1)])
    c = paddle.matmul(a, b)
    np.testing.assert_allclose(
        np.asarray(c._value),
        np.asarray(a._value) @ np.asarray(b._value), rtol=1e-5)


def test_dtensor_from_fn(mesh2d):
    t = dist.dtensor_from_fn(
        lambda: paddle.zeros(shape=[8, 4]), mesh2d, [Shard(0)])
    assert t.shape == [8, 4]
    assert t._value.addressable_shards[0].data.shape == (2, 4)


def test_shard_layer_replicates(mesh2d):
    import paddle_tpu.nn as nn

    layer = nn.Linear(8, 8)
    dist.shard_layer(layer, mesh2d)
    for p in layer.parameters():
        assert len(p._value.addressable_shards) == 8
        assert p._value.addressable_shards[0].data.shape == tuple(p.shape)


def test_collective_api_single_controller(mesh2d):
    dist.init_parallel_env()
    assert dist.get_world_size() == 1  # one controller process
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = dist.all_reduce(x)
    np.testing.assert_allclose(np.asarray(y._value), np.ones((4, 4)))
    out = []
    xs = dist.shard_tensor(x, mesh2d, [Shard(0)])
    dist.all_gather(out, xs)
    assert len(out) == 4  # dp-axis blocks
    assert out[0].shape == [1, 4]


def test_comm_ops_inside_shard_map(mesh2d):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.jax_compat import shard_map

    from paddle_tpu.distributed import comm_ops

    jm = mesh2d.jax_mesh()
    x = jnp.arange(8.0)

    def body(x):
        return comm_ops.all_reduce(x, "dp")

    out = shard_map(body, mesh=jm, in_specs=P("dp"), out_specs=P("dp"))(x)
    # each dp shard (2 els after mp replication) sums over 4 dp members
    expected = np.array([0 + 2 + 4 + 6]) * np.ones(2)
    assert out.shape == (8,)


def test_megatron_fg_pair_grads(mesh2d):
    """f/g conjugate collectives: forward values and backward psum."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.jax_compat import shard_map

    from paddle_tpu.distributed import comm_ops

    jm = mesh2d.jax_mesh()
    w = jnp.ones((4,))

    def loss(w):
        def body(w):
            y = comm_ops.identity_bwd_allreduce(w, "mp")
            return comm_ops.allreduce_bwd_identity(y * 2.0, "mp")

        out = shard_map(body, mesh=jm, in_specs=P(), out_specs=P())(w)
        return out.sum()

    g = jax.grad(loss)(w)
    # forward: psum over mp (size 2) of 2*w -> 4*w; d/dw = 4 per element...
    # backward: g-op passes grad through, f-op psums over mp (2 copies).
    np.testing.assert_allclose(np.asarray(g), 4.0 * np.ones(4))


def test_data_parallel_wrapper(mesh2d):
    import paddle_tpu.nn as nn

    dist.set_mesh(dist.ProcessMesh(np.arange(8).reshape(8), ["dp"]))
    model = dist.DataParallel(nn.Linear(4, 2))
    x = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    y = model(x)
    assert y.shape == [8, 2]
    loss = y.sum()
    loss.backward()
    for p in model.parameters():
        assert p.grad is not None
    dist.set_mesh(None) if hasattr(dist, "set_mesh") else None
    dist.process_mesh._global_mesh = None
