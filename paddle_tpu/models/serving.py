"""Continuous batching over the paged KV cache — the serving scheduler.

Goes beyond the reference's in-tree serving (its kernel-level anchor is the
block/paged cache of paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu; the scheduler itself lives out of
tree in PaddleNLP's serving stack): requests of mixed lengths are admitted
into fixed SLOTS of a shared page pool, decode runs as compiled
multi-token SEGMENTS over all slots at PER-SLOT depths, and slots retire
and readmit between segments — so the chip never drains to serve one
straggler.

TPU-native shape: everything device-side is a fixed-shape compiled
program. Prefill programs per (prompt-length bucket x admission group
width) write new requests' KV into their slots' pages (power-of-two
widths, donated pools — a single admission pays a width-1 forward, not a
``max_slots``-wide one). ONE decode program scans a segment of steps over
the full slot batch, with per-slot lengths driving paged attention,
per-slot rope positions, and an active mask freezing finished slots.

The host loop is an OVERLAPPED scheduler (the ragged-paged-attention
serving discipline): segment N+1 is dispatched from segment N's DEVICE
outputs (token/lengths/active carry — no host round trip) while the host
consumes N's results, so the chip stays busy through host bookkeeping.
Whenever the host changes the slot mask in a way the device cannot see
(admission, abort, deadline retirement), the pipeline drains and the next
dispatch is a synchronous turn from host state. Sampling uses PER-REQUEST
key streams — a pure function of (engine seed, rid, token index) — so a
speculatively dispatched segment, a bisection replay, and the serial
schedule all emit bit-identical tokens. ``FLAGS_serving_pipeline=0``
selects the serial one-segment-at-a-time loop.

``warmup()`` AOT-compiles (``jit(...).lower().compile()``) every declared
(bucket x group-width) prefill shape plus the chunked-prefill and
decode-segment programs, and can wire JAX's persistent compilation cache,
so first-request latency and ``stats()`` throughput stop absorbing
compile time.

KV memory is a DYNAMIC PAGE POOL (``models/kv_pool.py``), not a frozen
slot->page map: a slot is granted pages for its prompt at admission and
grows lazily as decode crosses page boundaries; retirement frees them.
Admission is bounded by *available pages* — many short requests can be
in flight where one long one fit before — with
``serving.kv_pool_exhausted`` backpressure (the queue head defers, a
running decode never fails: if growth outruns the pool the youngest slot
is PREEMPTED back to the queue and later resumes bit-identically via its
per-request key stream). Prompt prefixes are shared COPY-ON-WRITE: full
prompt pages are content-hashed into a :class:`kv_pool.PrefixCache`, a
new request maps already-computed pages read-only and prefills only from
the first divergent token (a mid-page divergence pays one device page
copy), and refcounts keep shared pages alive across the owners'
retirements. Page-table CONTENTS change at grant time; traced shapes
never do — the zero-post-warmup-compile invariant holds through the
allocator path.
"""
from __future__ import annotations

import logging
import time
import uuid
import zlib
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core import perfwatch, telemetry
from ..core.flags import define_flag, flag
from ..core.resilience import (
    Deadline,
    InjectedFault,
    ServingUnavailable,
    bump_counter,
    inject,
)
from ..core.tensor import Tensor
from ..profiler import annotate
from .generation import _make_paged_cache, _sample_rows
from .kv_pool import PagePool, PrefixCache

__all__ = ["ContinuousBatchingEngine", "Request", "TERMINAL_STATES"]

logger = logging.getLogger("paddle_tpu.serving")

# Every terminal status the engine can stamp on a Request (the frontend
# adds admission-level "rejected"/"unavailable" on top). The router's
# retirement switch is CI-gated against this set
# (tests/test_no_bare_except.py): a new terminal state added here without
# a router handler fails the guard, not production traffic.
TERMINAL_STATES = frozenset({"ok", "timed_out", "failed", "cancelled"})

define_flag("FLAGS_serving_pipeline", True,
            "Overlap host bookkeeping with the next compiled decode "
            "segment in ContinuousBatchingEngine (0 = serial fallback: "
            "dispatch, wait, consume, one segment at a time)")

# serving-path metrics (module-level handles: registry reset zeroes them
# in place, so caching here is safe and keeps the hot-path cost at one
# lock). Names are documented in README "Observability" and CI-gated
# against orphaning (tests/test_telemetry_guard.py).
_M_TTFT = telemetry.histogram(
    "serving.ttft_s", "submit -> first token (queue wait included; "
    "fresh attempts only — token_base>0 failover continuations are "
    "excluded)")
_M_TOK = telemetry.histogram(
    "serving.token_latency_s", "mean per-token decode latency, observed "
    "once per retired request over its post-first-token stream")
_M_TOKENS = telemetry.counter(
    "serving.tokens_total", "tokens emitted by the engine scheduler")
_M_REQS = telemetry.counter(
    "serving.requests_total", "terminal request verdicts, by status")
_M_MEGA_SEG = telemetry.counter(
    "serving.megakernel_segments", "decode segments dispatched through "
    "the fused megakernel program (FLAGS_decode_megakernel)")
# KV-occupancy accounting (perfwatch): the measurement side of the
# paged-KV roadmap item — logical occupancy of the preallocated page
# pool, not PJRT allocator bytes (the pool is allocated up front; the
# watchdog gauges device.* cover the allocator).
_M_KV_BYTES = telemetry.gauge(
    "serving.kv_bytes_in_use", "KV bytes physically occupied by active "
    "slots (whole pages; a prefix-shared page counts ONCE, so the gauge "
    "never exceeds the pool)")
_M_KV_OCC = telemetry.gauge(
    "serving.kv_slot_occupancy", "active slots / total slots")
_M_KV_FRAG = telemetry.gauge(
    "serving.kv_fragmentation_pct", "allocated-but-unused tail of the "
    "pages GRANTED to active slots: 100 * (1 - used tokens / granted "
    "page capacity) — the waste the dynamic allocator bounds to less "
    "than one page per slot (the static slot map wasted the whole "
    "unreached slot tail)")
_M_KV_PAGES_FREE = telemetry.gauge(
    "serving.kv_pages_free", "KV pool pages on the free list (grantable "
    "to admissions and decode growth right now)")
_M_KV_PAGES_TOTAL = telemetry.gauge(
    "serving.kv_pages_total", "total allocatable KV pool pages (scratch "
    "pages excluded)")
_M_KV_SLOT_PAGES = telemetry.gauge(
    "serving.kv_slot_pages", "pages currently granted to one slot, by "
    "{slot=} — the per-slot view `obs kv` renders")
_M_PREFIX_HIT = telemetry.gauge(
    "serving.prefix_hit_rate", "prompt tokens served from the prefix "
    "cache / prompt tokens admitted, over the session")
_M_PREFIX_SAVED = telemetry.counter(
    "serving.prefix_tokens_saved", "prompt tokens whose prefill was "
    "skipped because a cached prefix page already held their KV")
_M_KV_REQ = telemetry.histogram(
    "serving.kv_request_bytes", "per-request KV footprint at retirement "
    "(prompt + emitted tokens, page-rounded)",
    buckets=tuple(float(2 ** p) for p in range(10, 31, 2)))
_M_KV_PINNED = telemetry.gauge(
    "serving.kv_pages_pinned_export", "pool pages pinned for KV export "
    "(prefill handoff holds, live transfer tickets, and partially "
    "imported chunks) — granted but invisible to the slot table, so "
    "`obs kv` pool-pressure readings stay honest")


_cwd = None


def _compile_watchdog():
    """Lazy jit-layer import (the jit package imports heavy deps)."""
    global _cwd
    if _cwd is None:
        from ..jit.compile_watch import compile_watchdog

        _cwd = compile_watchdog()
    return _cwd


class Request:
    """One in-flight generation request inside the engine scheduler.

    ``status`` lifecycle: ``pending`` → (``ok`` | ``timed_out`` |
    ``failed`` | ``cancelled``). ``tokens`` accumulates generated ids;
    ``poisoned`` is the sticky poison mark set when the
    ``serving.engine_fault`` injection site fires for this request, so
    bisection retries fail deterministically on the same offender.

    ``token_base`` is the request's sampling-stream offset: a FAILOVER
    RESUME (router resubmitting a request stranded on a dead replica)
    submits ``original prompt + the k tokens already emitted`` as the
    prompt with ``token_base=k``, so the first token sampled here is
    stream index ``k`` — bit-identical to the continuation the
    uninterrupted run would have produced.

    ``trace`` is the request's telemetry trace id (minted by the router
    or frontend, riding the RPC envelope across processes); dispatch
    spans and the retire event carry it so one rid's whole life — queue
    wait, prefill, every decode segment, failover hops — stitches into
    one timeline. ``t_submit``/``t_first`` anchor the TTFT and per-token
    latency histograms (monotonic; ``t_submit`` is overwritten by the
    frontend with its own admission stamp so queue wait counts).

    ``hold_kv`` marks a disaggregated PREFILL request: on "ok"
    retirement the slot's page grants move to the engine's export hold
    table (refcounts intact) instead of the free list, awaiting an
    ``export_pages`` ticket. ``kv_import`` names a completed import ticket
    a DECODE-side request adopts at admission — the request seats
    directly onto the imported pages with the prefill's first token
    already emitted, no prefill dispatch.
    """

    __slots__ = ("rid", "prompt", "max_new_tokens", "deadline", "tokens",
                 "status", "poisoned", "poison_checked", "error",
                 "token_base", "trace", "t_submit", "t_first", "tenant",
                 "preempted", "hold_kv", "kv_import")

    def __init__(self, rid, prompt, max_new_tokens, deadline=None,
                 token_base=0, trace=None, tenant=None, hold_kv=False,
                 kv_import=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline or Deadline.never()
        self.tokens: list[int] = []
        self.status = "pending"
        self.poisoned = False
        self.poison_checked = False
        self.error = None
        self.token_base = int(token_base)
        self.trace = trace
        self.tenant = tenant
        self.t_submit = time.monotonic()
        self.t_first = None
        # set when the engine pulled this request off its slot to free
        # pages (pool exhaustion): re-admission then requires coverage
        # to the request's FULL budget so it cannot thrash in and out
        self.preempted = False
        self.hold_kv = bool(hold_kv)
        self.kv_import = kv_import

    def output(self):
        return np.asarray(self.tokens[:self.max_new_tokens], np.int32)

    def __repr__(self):
        return (f"Request(rid={self.rid}, len={self.prompt.size}, "
                f"status={self.status!r})")


def _bucket(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket {buckets[-1]}")


# splitmix64 constants for the per-request key streams: a vectorized
# counter-based hash (pure uint64 arithmetic, stable across numpy
# versions) instead of per-token SeedSequence objects, which would put
# O(segment x slots) Python-object work on the dispatch critical path
_SM64_A = np.uint64(0x9E3779B97F4A7C15)
_SM64_B = np.uint64(0xBF58476D1CE4E5B9)
_SM64_C = np.uint64(0x94D049BB133111EB)

# fixed operand width of the copy-on-write page-copy program: one
# compiled shape regardless of how many pages a step copies (padding
# lanes copy the dump page onto itself; larger batches loop)
_COW_WIDTH = 8

# fixed chunk width (in pages) of the KV export/import transfer
# programs: like _COW_WIDTH, one compiled shape regardless of how many
# pages a ticket moves — partial chunks pad with the dump page on both
# sides (the source gathers garbage from it, the destination scatters
# that garbage back onto its own dump page; never read)
_XFER_WIDTH = 4


def _mix64(x):
    x = (x ^ (x >> np.uint64(30))) * _SM64_B
    x = (x ^ (x >> np.uint64(27))) * _SM64_C
    return x ^ (x >> np.uint64(31))


class ContinuousBatchingEngine:
    """Mixed-length generation over ``max_slots`` concurrent sequences.

    Prompts up to the largest bucket admit in one padded prefill; LONGER
    prompts admit via CHUNKED PREFILL — full largest-bucket-wide chunks
    written at per-slot offsets (requires ``max_len`` to be a multiple of
    the largest bucket), so long-context requests stream in without a
    dedicated compiled shape per length.

    Usage::

        eng = ContinuousBatchingEngine(model, max_slots=8, max_len=512)
        eng.warmup(segment=16)   # optional: AOT-compile every shape
        outs, stats = eng.run(prompts, max_new_tokens=64, segment=16)

    ``pipeline=None`` (default) follows ``FLAGS_serving_pipeline``;
    ``pipeline=False`` forces the serial scheduler for this engine.
    """

    # The fused decode megakernel keeps residual + post-attention norm
    # INSIDE the per-layer kernel, right after o_proj. Subclasses whose
    # o_proj output is a PARTIAL sum (TP row-parallel needs a psum
    # before the residual) must opt out.
    _megakernel_ok = True

    def __init__(self, model, max_slots, max_len, page_size=128,
                 do_sample=False, temperature=1.0, top_k=None, top_p=None,
                 eos_token_id=None, prompt_buckets=(16, 32, 64, 128),
                 seed=0, pipeline=None, pool_pages=None, prefix_cache=True):
        from ..jit import _FunctionalModel, _swap_lock

        model.eval()
        cfg = model.config
        self.model = model
        self.cfg = cfg
        self.max_slots = int(max_slots)
        page_size = min(page_size, max_len)
        if max_len % page_size:
            rounded = -(-max_len // page_size) * page_size
            # the round-up changes the caller's budget (prompt+max_new
            # validation runs against the EFFECTIVE capacity): say so
            # once and surface it in stats()["kv"]["max_len"]
            logger.warning(
                "ContinuousBatchingEngine: max_len %d rounded up to %d "
                "(a multiple of page_size %d); stats()['kv'] reports "
                "the effective value", max_len, rounded, page_size)
            self._max_len_rounded_from = int(max_len)
            max_len = rounded
        else:
            self._max_len_rounded_from = None
        self.max_len = int(max_len)
        self.page_size = int(page_size)
        self.do_sample = bool(do_sample)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.eos_token_id = eos_token_id
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.pipeline_opt = pipeline
        kv = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        try:
            dtype = next(iter(model.parameters()))._value.dtype
        except StopIteration:
            dtype = jnp.float32
        per_seq = self.max_len // self.page_size
        self._cols = per_seq  # attention-visible table columns
        # DYNAMIC POOL: ``pool_pages`` allocatable pages shared by every
        # slot (default: the historical budget of one full-length
        # sequence per slot, so the device arrays are byte-identical to
        # the static layout) + SCRATCH pages: admission groups are
        # padded to a fixed power-of-two batch width (one compiled
        # prefill shape per bucket x width, not one per group size) and
        # padding rows write into scratch, never into a live slot's
        # pages. Padding rows write at most chunk_w tokens (base 0), so
        # scratch holds chunk_w/page pages.
        chunk_w = self.prompt_buckets[-1]
        scratch_np = max(chunk_w // self.page_size, 1)
        n_real = (self.max_slots * per_seq if pool_pages is None
                  else int(pool_pages))
        if n_real < per_seq:
            raise ValueError(
                f"pool_pages {n_real} cannot hold one full-length "
                f"sequence ({per_seq} pages of {self.page_size} tokens "
                f"for max_len {self.max_len})")
        self._pool_pages = n_real
        n_pages = n_real + scratch_np
        # table rows carry EXTRA trailing scratch-aliased columns: a
        # prefix-resume prefill writes a padded bucket at an arbitrary
        # base, so its (masked, never-read) padding tail can spill up to
        # chunk_w tokens past max_len — those positions must map to a
        # scratch page, not clamp onto a live one
        self._extra_cols = -(-chunk_w // self.page_size)
        total_cols = per_seq + self._extra_cols
        self._nl = cfg.num_hidden_layers
        self._ks = [jnp.zeros((n_pages, self.page_size, kv, cfg.head_dim),
                              dtype) for _ in range(self._nl)]
        self._vs = [jnp.zeros_like(k) for k in self._ks]
        # any table cell not backed by a granted page aliases the DUMP
        # page (the last scratch page): writes there are garbage by
        # construction and reads never reach it (attention masks by
        # length < max_len)
        self._dump_page = n_real + scratch_np - 1
        scratch_ids = n_real + np.minimum(
            np.arange(total_cols, dtype=np.int32), scratch_np - 1)
        # host page table: slot rows are rebuilt from the allocator's
        # grants (_set_table_row); row ``max_slots`` is the scratch row.
        # Kept NUMPY-side for prefill row gathers — the post-warmup hot
        # path must not trigger a single compilation; the device copy
        # (_tables_device) is re-uploaded on grant, never re-traced.
        self._tables_np = np.full((self.max_slots + 1, total_cols),
                                  self._dump_page, np.int32)
        self._tables_np[self.max_slots] = scratch_ids
        # per-segment invariants hoisted out of the dispatch loop: the
        # device table/limits copies change only at grant/admission and
        # are invalidated there
        self._tables_active = None
        self._limits_dev = None
        self._pool = PagePool(n_real)
        self._prefix = (PrefixCache(self._pool, self.page_size,
                                    self._recycle)
                        if prefix_cache else None)
        self._slot_pages: list[list] = [[] for _ in range(self.max_slots)]
        # quarantine for freed pages that a dispatched-but-unconsumed
        # program may still write (see _recycle/_mark_executed)
        self._quarantine: list = []
        self._disp_n = 0
        self._exec_floor = 0
        self._functional = _FunctionalModel(model)
        # param/buffer snapshots must not race another engine's trace-time
        # param swap on a SHARED model (tracers would leak into the
        # snapshot and outlive their trace) — serialize on the swap lock
        self._swap_lock = _swap_lock
        with _swap_lock:
            self._buffers = {k: b._value for k, b in model.named_buffers()}
        self._zero_key = jax.random.key_data(jax.random.PRNGKey(0))
        self._key_shape = tuple(self._zero_key.shape)
        self._key_size = int(np.prod(self._key_shape))
        # sampling keys are fabricated HOST-side as PER-REQUEST streams:
        # key(rid, t) is a pure function of (seed, rid, token index), so
        # token streams never depend on batching, bisection replays, or
        # pipeline speculation — and cost no device dispatches
        self._seed = int(seed)
        self._zeros_cache: dict[tuple, jnp.ndarray] = {}
        self._aot: dict[tuple, object] = {}
        # KV accounting invariants (perfwatch): bytes one token's K+V
        # rows cost across all layers, at the cache dtype
        self._kv_bytes_per_token = int(
            self._nl * 2 * kv * cfg.head_dim * np.dtype(dtype).itemsize)
        self._warmed = False
        self._prefill_p = None
        self._segment_p = None
        # fused decode path (FLAGS_decode_megakernel): decided ONCE per
        # engine — the fused segment program is built and AOT-warmed
        # only when the model passes the capability probe, so the
        # zero-post-warmup-compile invariant covers both paths
        from ..ops.pallas.decode_megakernel import megakernel_model_supported

        self._megakernel = (int(flag("FLAGS_decode_megakernel")) > 0
                            and type(self)._megakernel_ok
                            and megakernel_model_supported(model))
        self._build_programs()

    # -------------------------------------------- page recycling safety
    #
    # A freed page may still be WRITTEN by a program that was dispatched
    # before the free (every dispatched segment writes every slot's
    # current cell, frozen slots included). Device programs execute in
    # dispatch order, so a page is safe to re-grant once every program
    # dispatched before the free has provably executed — which a
    # blocking fetch of any LATER (or the same) program's outputs
    # proves. ``_disp_n`` counts dispatches; ``_exec_floor`` is the
    # highest dispatch index proven executed; frees tagged above the
    # floor wait in quarantine.

    def _mark_dispatch(self) -> int:
        self._disp_n += 1
        return self._disp_n

    def _mark_executed(self, d):
        if d <= self._exec_floor:
            return
        self._exec_floor = d
        if self._quarantine:
            keep = []
            for tag, pages in self._quarantine:
                if tag <= self._exec_floor:
                    self._pool.recycle(pages)
                else:
                    keep.append((tag, pages))
            self._quarantine = keep

    def _recycle(self, pages):
        """Zero-ref pages back to the free list — immediately when no
        possibly-unexecuted program can write them, else quarantined."""
        if not pages:
            return
        if self._exec_floor >= self._disp_n:
            self._pool.recycle(pages)
        else:
            self._quarantine.append((self._disp_n, pages))

    # --------------------------------------------------- page-table state

    def _set_table_row(self, slot):
        """Mirror the slot's granted pages into its host table row (tail
        columns alias the dump page) and invalidate the device copy —
        contents change, the traced shape never does."""
        row = self._tables_np[slot]
        pages = self._slot_pages[slot]
        row[:len(pages)] = pages
        row[len(pages):] = self._dump_page
        self._tables_active = None

    def _tables_device(self):
        """Device copy of the active slot rows, rebuilt after any page
        grant (a host->device upload, never a compilation). The TP
        engine overrides this to commit the upload mesh-replicated."""
        if self._tables_active is None:
            self._tables_active = jnp.asarray(
                self._tables_np[:self.max_slots])
        return self._tables_active

    def _free_slot_pages(self, slot):
        """Release the slot's page grants (shared pages just drop one
        reference; cache-held prefix pages survive for future hits)."""
        pages, self._slot_pages[slot] = self._slot_pages[slot], []
        if pages:
            self._recycle(self._pool.decref(pages))
            self._set_table_row(slot)

    # ------------------------------------------------------------ programs

    def _caches(self, ks, vs, tables, length, aligned=None):
        # chunked-prefill bases are chunk_w multiples: page-aligned (the
        # bulk-write opt-in) exactly when chunk_w is a page multiple;
        # the prefix-RESUME path passes aligned=False — its bases start
        # at the first divergent token, which may sit mid-page
        if aligned is None:
            aligned = self.prompt_buckets[-1] % self.page_size == 0
        return [_make_paged_cache(ks[i], vs[i], tables, self.page_size,
                                  length, aligned_bases=aligned,
                                  attn_pages=self._cols,
                                  dump_page=self._dump_page)
                for i in range(self._nl)]

    def _build_programs(self):
        functional = self._functional
        buffers = self._buffers
        zero_key = self._zero_key
        temperature, top_k, top_p = self.temperature, self.top_k, self.top_p
        greedy = not self.do_sample
        eos = self.eos_token_id

        def sample_batch(last, keys):
            # per-row key streams: row i is drawn with ITS OWN key, so a
            # row's tokens are independent of who it was batched with
            return _sample_rows(last, keys, temperature, top_k, top_p,
                                greedy).astype(jnp.int32)

        def sample_true_last(logits, true_lens, keys):
            # first token from each row's TRUE last position (padding
            # rows are never read — causal)
            idx = (true_lens - 1).astype(jnp.int32)[:, None, None]
            last = jnp.take_along_axis(
                logits, jnp.broadcast_to(
                    idx, (logits.shape[0], 1, logits.shape[-1])),
                axis=1)[:, 0]
            return sample_batch(last, keys)

        def write_prompts(params, ks, vs, prompts, table_rows, base):
            # run the model over (N, L) prompt rows writing each row's
            # slot pages at ``base`` (0 = fresh slots, (N,) array =
            # chunked-prefill offsets); returns (logits, pools)
            caches = self._caches(ks, vs, table_rows, base)
            (logits, caches2), _ = functional(
                params, buffers, (prompts,), {"caches": caches}, zero_key)
            return (logits, [c.k_pages for c in caches2],
                    [c.v_pages for c in caches2])

        def prefill(params, ks, vs, prompts, table_rows, true_lens, keys):
            # N same-bucket admissions in ONE dispatch (static zero base:
            # the fast causal prefill path)
            logits, ks2, vs2 = write_prompts(
                params, ks, vs, prompts, table_rows, 0)
            return sample_true_last(logits, true_lens, keys), ks2, vs2

        def chunk_step(params, ks, vs, chunk, table_rows, bases):
            # CHUNKED PREFILL body: write one full chunk of a long prompt
            # at per-row base offsets (rows attend causally to everything
            # already in their slot) — no sampling, pools out
            _, ks2, vs2 = write_prompts(
                params, ks, vs, chunk, table_rows, bases)
            return ks2, vs2

        def final_chunk(params, ks, vs, chunk, table_rows, bases, true_lens,
                        keys):
            # last (padded) chunk of a long prompt: write + sample
            logits, ks2, vs2 = write_prompts(
                params, ks, vs, chunk, table_rows, bases)
            return sample_true_last(logits, true_lens, keys), ks2, vs2

        def resume_final(params, ks, vs, chunk, table_rows, bases,
                         true_lens, keys):
            # PREFIX-RESUME prefill: the divergent tail of a prompt whose
            # head was served from the prefix cache — written at per-row
            # bases that may sit MID-PAGE (unaligned scatter path; the
            # CoW page copy ran first), sampling at the true last token
            caches = self._caches(ks, vs, table_rows, bases,
                                  aligned=False)
            (logits, caches2), _ = functional(
                params, buffers, (chunk,), {"caches": caches}, zero_key)
            ks2 = [c.k_pages for c in caches2]
            vs2 = [c.v_pages for c in caches2]
            return sample_true_last(logits, true_lens, keys), ks2, vs2

        def cow_copy(params, ks, vs, src, dst):
            # copy-on-write page copy: duplicate shared pages a writer
            # must append into (params ride for dispatch uniformity —
            # XLA dead-code-eliminates them). Padding lanes copy the
            # dump page onto itself.
            ks2 = [k.at[dst].set(k[src]) for k in ks]
            vs2 = [v.at[dst].set(v[src]) for v in vs]
            return ks2, vs2

        def export_pages(params, ks, vs, idx):
            # KV page EXPORT (disaggregation handoff, source side):
            # gather one fixed-width chunk of pages from every layer
            # into a single host-fetchable (layers, W, page, kv, hd)
            # payload pair. The donated pools alias straight through
            # unmodified; params ride for dispatch uniformity.
            payk = jnp.stack([k[idx] for k in ks])
            payv = jnp.stack([v[idx] for v in vs])
            return ks, vs, payk, payv

        def import_pages(params, ks, vs, idx, payk, payv):
            # KV page IMPORT (destination side): scatter one received
            # chunk into locally granted pages. Padding lanes write the
            # dump page (source padded the payload with its own dump
            # page — garbage lands on garbage, never read).
            ks2 = [k.at[idx].set(payk[i]) for i, k in enumerate(ks)]
            vs2 = [v.at[idx].set(payv[i]) for i, v in enumerate(vs)]
            return ks2, vs2

        def segment(params, ks, vs, tables, lengths, toks, active, limits,
                    keys):
            def body(carry, key):
                tok, ks, vs, lengths, active = carry
                caches = self._caches(ks, vs, tables, lengths)
                (logits, caches2), _ = functional(
                    params, buffers, (tok[:, None],), {"caches": caches},
                    zero_key)
                nxt = sample_batch(logits[:, -1, :], key)
                nxt = jnp.where(active, nxt, tok)  # frozen slots emit noise
                new_lengths = jnp.where(active, lengths + 1, lengths)
                # deactivate at the per-slot token budget: a slot must
                # never advance past its validated capacity mid-segment
                # (the paged kernel's lengths contract; frozen slots
                # re-write their own frozen cell, never another slot's)
                new_active = active & (new_lengths < limits)
                if eos is not None:
                    new_active = new_active & (nxt != eos)
                ks2 = [c.k_pages for c in caches2]
                vs2 = [c.v_pages for c in caches2]
                return ((nxt, ks2, vs2, new_lengths, new_active),
                        (nxt, active))

            (tok, ks, vs, lengths, active), (emitted, was_active) = \
                jax.lax.scan(body, (toks, ks, vs, lengths, active), keys)
            return emitted, was_active, tok, lengths, active, ks, vs

        self._prefill_p = jax.jit(prefill, donate_argnums=(1, 2))
        self._chunk_p = jax.jit(chunk_step, donate_argnums=(1, 2))
        self._final_chunk_p = jax.jit(final_chunk, donate_argnums=(1, 2))
        self._resume_p = jax.jit(resume_final, donate_argnums=(1, 2))
        self._cow_p = jax.jit(cow_copy, donate_argnums=(1, 2))
        self._export_p = jax.jit(export_pages, donate_argnums=(1, 2))
        self._import_p = jax.jit(import_pages, donate_argnums=(1, 2))
        from ..ops.pallas.decode_megakernel import megakernel_scope

        def segment_unfused(*args):
            # scope(False): the per-layer megakernel hook must not fire
            # in a declined engine's program even under forced-kernel
            # flag modes — this program IS the unfused reference
            with megakernel_scope(False):
                return segment(*args)

        def segment_fused(*args):
            with megakernel_scope(True):
                return segment(*args)

        # ONE segment program, shape decided by the construction-time
        # probe (self._megakernel): every caller — dispatch, bisection
        # replay, fault-injecting tests that monkeypatch _segment_p —
        # sees the same program either way.
        if self._megakernel:
            from ..jit.fusion import fuse_elementwise_chains

            self._segment_p = jax.jit(
                fuse_elementwise_chains(segment_fused),
                donate_argnums=(1, 2))
        else:
            self._segment_p = jax.jit(segment_unfused,
                                      donate_argnums=(1, 2))

    # --------------------------------------------------- program dispatch

    def _call(self, key, fallback, *args):
        """Dispatch through the AOT-compiled executable when ``warmup()``
        built one for this shape, else through the lazily-compiling jitted
        program (``fallback`` is looked up at call time so tests can
        monkeypatch ``_segment_p``/``_chunk_p``/...).

        On a WARMED engine the fallback path is itself the anomaly —
        this shape was not in the warmup set — so it runs inside the
        compile watchdog's dispatch context: if XLA compiles in there,
        the watchdog counts ``xla.compiles_total{phase=serving}`` and
        dumps a flight record naming ``key`` and the operand shapes."""
        exe = self._aot.get(key)
        if exe is not None:
            return exe(*args)
        if self._warmed and telemetry.enabled():
            # operand shapes: skip params/ks/vs (their shapes are
            # engine-static); the trailing args carry the traced shape
            # that missed the warmup set
            shapes = [list(a.shape) for a in args[3:]
                      if hasattr(a, "shape")]
            with _compile_watchdog().dispatch_context(key, shapes=shapes):
                return fallback(*args)
        return fallback(*args)

    def _group_width(self, n):
        """Smallest power-of-two admission batch width >= n, capped at
        ``max_slots`` — the compiled prefill shape this group rides."""
        w = 1
        while w < n:
            w <<= 1
        return min(w, self.max_slots)

    def group_widths(self):
        """Every compiled admission width: {1, 2, 4, ..., max_slots}."""
        out = []
        w = 1
        while w < self.max_slots:
            out.append(w)
            w <<= 1
        out.append(self.max_slots)
        return tuple(out)

    def warmup(self, segment=None, cache_dir=None):
        """AOT-compile (``jit(...).lower().compile()``) every declared
        serving shape: one prefill program per (prompt bucket x admission
        group width), the chunked-prefill chunk/final programs per width
        (when ``max_len`` admits chunking), and the decode-segment program
        at ``segment`` steps. After warmup a ``run()``/``step()`` session
        over in-bucket prompts triggers ZERO compilations — first-request
        latency and ``stats()['tokens_per_sec']`` stop absorbing compile
        time.

        ``segment`` must match the segment length later sessions use
        (defaults to the last ``start(segment=...)`` or 16).
        ``cache_dir`` additionally wires JAX's persistent compilation
        cache so the compiles survive process restarts. Returns
        ``{"programs": newly compiled, "cached": already present,
        "seconds": wall}``.
        """
        if cache_dir is not None:
            from ..jit import enable_compilation_cache

            enable_compilation_cache(cache_dir)
        t0 = time.monotonic()
        # compile watchdog: everything below is warmup-phase compilation;
        # once done, this engine's non-AOT dispatches become recompile
        # incidents (see _call)
        wd = _compile_watchdog().start()
        with wd.warmup_scope():
            stats = self._warmup_compile(segment)
        self._warmed = True
        wd.arm()
        stats["seconds"] = time.monotonic() - t0
        return stats

    def _sds(self, x):
        """Warmup aval for an EXISTING engine array (params / KV pools).
        The TP engine (models/tp_serving.py) overrides this to carry the
        array's committed mesh sharding into the AOT lowering — an
        executable compiled without shardings refuses sharded inputs."""
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)

    def _op_aval(self, shape, dtype):
        """Warmup aval for an operand fabricated host-side per dispatch
        (prompts, table rows, sampling keys). The TP engine overrides
        this to pin them replicated over its mesh."""
        return jax.ShapeDtypeStruct(shape, dtype)

    def _param_snapshot(self):
        """The param dict a session (or warmup lowering) runs against.
        The TP engine overrides this to serve MESH-SHARDED copies
        without mutating the model — a collocated single-chip engine
        sharing the same model must keep seeing unsharded params."""
        return {k: p._value for k, p in self.model.named_parameters()}

    def _warmup_compile(self, segment):
        """The warmup compile loop (split out so :meth:`warmup` can
        scope it under the compile watchdog)."""
        with self._swap_lock:
            params = self._param_snapshot()
        sds = self._sds
        p_s = jax.tree_util.tree_map(sds, params)
        ks_s = [sds(k) for k in self._ks]
        vs_s = [sds(v) for v in self._vs]
        kdt = self._zero_key.dtype
        cols = self._tables_np.shape[1]
        i32 = jnp.int32
        stats = {"programs": 0, "cached": 0}

        def compile_(key, jitted, *avals):
            if key in self._aot:
                stats["cached"] += 1
                return
            self._aot[key] = jitted.lower(p_s, ks_s, vs_s, *avals).compile()
            stats["programs"] += 1

        chunk_w = self.prompt_buckets[-1]
        for g in self.group_widths():
            rows_s = self._op_aval((g, cols), i32)
            lens_s = self._op_aval((g,), i32)
            keys_s = self._op_aval((g,) + self._key_shape, kdt)
            for bucket in self.prompt_buckets:
                compile_(("prefill", bucket, g), self._prefill_p,
                         self._op_aval((g, bucket), i32),
                         rows_s, lens_s, keys_s)
                if self._prefix is not None:
                    # prefix-resume prefill: same (bucket x width) grid,
                    # plus the per-row base operand
                    compile_(("resume", bucket, g), self._resume_p,
                             self._op_aval((g, bucket), i32),
                             rows_s, self._op_aval((g,), i32),
                             lens_s, keys_s)
            if self.max_len > chunk_w and (
                    self.max_len % chunk_w == 0
                    or self._pool_pages < self.max_slots * self._cols):
                # beyond submitted long prompts (which _validate rejects
                # on non-multiple engines), a PREEMPTED request whose
                # folded prompt outgrew chunk_w re-admits through the
                # chunked path (final-chunk overflow lands in the extra
                # dump-aliased columns) — so the programs must also be
                # warmed on non-multiple engines whose RESTRICTED pool
                # can actually exhaust; the default full pool cannot
                # (every slot fits a whole sequence), so those engines
                # skip the dead compiles
                chunk_s = self._op_aval((g, chunk_w), i32)
                bases_s = self._op_aval((g,), i32)
                compile_(("chunk", g), self._chunk_p, chunk_s, rows_s,
                         bases_s)
                compile_(("final", g), self._final_chunk_p, chunk_s, rows_s,
                         bases_s, lens_s, keys_s)
        if self._prefix is not None:
            compile_(("cow", _COW_WIDTH), self._cow_p,
                     self._op_aval((_COW_WIDTH,), i32),
                     self._op_aval((_COW_WIDTH,), i32))
        # KV page transfer (prefill/decode disaggregation): the fixed-
        # width export/import chunk programs, warmed so page payloads
        # move between replicas without a single post-warmup trace
        xfer_idx_s = self._op_aval((_XFER_WIDTH,), i32)
        pay_s = self._op_aval(
            (len(self._ks), _XFER_WIDTH) + tuple(self._ks[0].shape[1:]),
            self._ks[0].dtype)
        compile_(("export", _XFER_WIDTH), self._export_p, xfer_idx_s)
        compile_(("import", _XFER_WIDTH), self._import_p, xfer_idx_s,
                 pay_s, pay_s)
        seg = int(segment if segment is not None
                  else getattr(self, "_segment_len", 16))
        m = self.max_slots
        seg_avals = (self._op_aval((m, cols), i32),
                     self._op_aval((m,), i32),
                     self._op_aval((m,), i32),
                     self._op_aval((m,), jnp.bool_),
                     self._op_aval((m,), i32),
                     self._op_aval((seg, m) + self._key_shape, kdt))
        compile_(("segment", seg), self._segment_p, *seg_avals)
        return stats

    # ------------------------------------------------------- sampling keys

    def _key_zeros(self, shape):
        # greedy sampling ignores keys: serve a cached device-resident
        # zeros array (built via device_put, never a compiled fill)
        arr = self._zeros_cache.get(shape)
        if arr is None:
            arr = jnp.asarray(np.zeros(shape, np.uint32).astype(
                self._zero_key.dtype))
            self._zeros_cache[shape] = arr
        return arr

    def _rid_seed(self, rid):
        """Per-request stream root — a pure function of (engine seed,
        rid), so token streams are identical whether a token is produced
        by the serial loop, a speculative pipelined segment, or a
        bisection replay."""
        try:
            r = int(rid) & 0xFFFFFFFFFFFFFFFF
        except (TypeError, ValueError):
            r = zlib.crc32(str(rid).encode())
        # shape-(1,) operands: numpy wraps ARRAY uint64 overflow silently
        # (the intended mod-2^64 arithmetic) but warns on scalars
        return _mix64(np.asarray([self._seed], np.uint64) * _SM64_A
                      + np.asarray([r], np.uint64) * _SM64_B
                      + np.uint64(1))

    def _req_key_block(self, rid, base, n):
        """(n, key_size) uint32 key-data words for request ``rid``'s
        tokens ``base .. base+n-1`` — one vectorized hash over the
        (token index, word) grid, no per-token Python objects."""
        t = (np.uint64(base)
             + np.arange(n, dtype=np.uint64))[:, None]
        w = np.arange(1, self._key_size + 1, dtype=np.uint64)[None, :]
        h = _mix64(self._rid_seed(rid) + t * _SM64_A + w * _SM64_C)
        return (h >> np.uint64(32)).astype(np.uint32)

    def _prefill_keys(self, group, g):
        # first token of each admitted request: index ``token_base +
        # already-emitted`` of its stream (0 for fresh requests; k for a
        # failover resume that emitted k tokens elsewhere; the emitted
        # count for a PREEMPTED request re-admitting with its partial
        # output folded into the prompt)
        shape = (g,) + self._key_shape
        if not self.do_sample:
            return self._key_zeros(shape)
        bits = np.zeros(shape, np.uint32)
        for i, (_, req) in enumerate(group):
            bits[i] = self._req_key_block(
                req.rid, req.token_base + len(req.tokens),
                1).reshape(self._key_shape)
        return jnp.asarray(bits)

    def _segment_keys(self, offset):
        """Keys for one decode segment: slot s step i uses its request's
        stream at index ``len(tokens) + offset + i``. ``offset`` is the
        in-flight emission count a speculative dispatch must skip past
        (``segment_len`` when one segment is unconsumed, else 0)."""
        seg = self._segment_len
        shape = (seg, self.max_slots) + self._key_shape
        if not self.do_sample:
            return self._key_zeros(shape)
        bits = np.zeros(shape, np.uint32)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            bits[:, slot] = self._req_key_block(
                req.rid, req.token_base + len(req.tokens) + offset,
                seg).reshape((seg,) + self._key_shape)
        return jnp.asarray(bits)

    # ----------------------------------------------------------- scheduler
    #
    # The engine is a STEPWISE scheduler: ``start()`` resets a session,
    # ``submit()`` enqueues requests (over time — the ServingFrontend
    # feeds it incrementally), ``step()`` performs one admit → decode →
    # retire turn and returns the requests that finished, ``abort()``
    # pulls a request back out. ``run()`` below is the batch convenience
    # wrapper that submits a whole list and steps to completion.

    def _validate(self, prompt, max_new_tokens):
        """Reject a request whose prefill could write outside its slot's
        pages — BEFORE any work is dispatched for it."""
        chunk_w = self.prompt_buckets[-1]
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds slot capacity {self.max_len}")
        # validate buckets UP FRONT: prefill writes the whole padded
        # bucket/chunk into the slot's pages, and an oversized bucket
        # must not surface mid-run after other requests' work
        if prompt.size <= chunk_w:
            b = _bucket(prompt.size, self.prompt_buckets)
            if b > self.max_len:
                raise ValueError(
                    f"prompt bucket {b} (for a {prompt.size}-token prompt) "
                    f"exceeds slot capacity {self.max_len}; add a "
                    f"smaller bucket or raise max_len")
        elif self.max_len % chunk_w:
            # chunked prefill pads the final chunk to chunk_w; the
            # write stays inside the slot's pages iff chunk_w divides
            # the capacity
            raise ValueError(
                f"chunked prefill (prompt {prompt.size} > largest bucket "
                f"{chunk_w}) requires max_len ({self.max_len}) to be "
                f"a multiple of the largest bucket")

    def start(self, segment=16, run_deadline=None):
        """Reset the scheduler for a new serving session: snapshot the
        parameters, clear slots/queue/counters. ``segment`` is the compiled
        decode window per ``step()``; ``run_deadline`` bounds the whole
        session (unfinished requests retire as ``timed_out`` past it)."""
        with self._swap_lock:
            self._params = self._param_snapshot()
        self._segment_len = int(segment)
        self._run_deadline = run_deadline or Deadline.never()
        self._queue: deque[Request] = deque()
        self._slot_req: list[Request | None] = [None] * self.max_slots
        # allocator session reset: every grant returns to the pool and
        # the PREFIX CACHE is cleared — the param snapshot above may
        # differ from the one the cached KV was computed under
        self._pool = PagePool(self._pool_pages)
        if self._prefix is not None:
            self._prefix = PrefixCache(self._pool, self.page_size,
                                       self._recycle)
        self._slot_pages = [[] for _ in range(self.max_slots)]
        # KV transfer state (disaggregation): holds are "ok" hold_kv
        # retirements awaiting a ticket; exports are live tickets;
        # imports are destination-side chunk landings. All pin pool
        # pages via refcounts — the fresh pool above dropped them all.
        self._kv_holds = {}
        self._exports = {}
        self._export_by_rid = {}
        self._imports = {}
        self._quarantine = []
        self._disp_n = 0
        self._exec_floor = 0
        self._tables_np[:self.max_slots] = self._dump_page
        self._tables_active = None
        self._slot_adm = [0] * self.max_slots  # admission seq per slot
        self._adm_seq = 0
        self._resume_base = {}
        self._cow_pair = {}
        self.admission_blocked = False  # pool deferred the queue head
        self._prefix_lookup_tokens = 0
        self._prefix_hit_tokens = 0
        self._lengths = np.ones((self.max_slots,), np.int32)  # idle: len 1
        self._cur_tok = np.zeros((self.max_slots,), np.int32)
        # per-slot length budget: prompt + max_new - 1 is the final length
        # the last needed emission reaches; the segment program deactivates
        # a slot there so it never advances past validated capacity
        self._limits = np.full((self.max_slots,), self.max_len, np.int32)
        self._limits_dev = None
        self._useful = 0
        self._seg_runs = 0
        # occupancy as running sum/count: a long-lived serving session
        # must not grow a per-step list without bound
        self._occ_sum = 0.0
        self._occ_n = 0
        self._counts = {"ok": 0, "timed_out": 0, "failed": 0,
                        "cancelled": 0, "rejected": 0}
        self._auto_rid = 0
        # pipeline state: at most ONE dispatched-but-unconsumed segment;
        # ``_dirty`` marks host mask changes the device cannot see
        # (abort / deadline retirement), forcing a drain + sync turn
        self._pipeline = (bool(flag("FLAGS_serving_pipeline"))
                          if self.pipeline_opt is None
                          else bool(self.pipeline_opt))
        self._inflight = None
        self._dirty = False
        # host-gap accounting: time from finishing one segment's host
        # bookkeeping to issuing the next dispatch
        self._gap_sum = 0.0
        self._gap_n = 0
        self._t_host0 = None
        self._t0 = time.monotonic()
        return self

    def submit(self, prompt, max_new_tokens, deadline_s=None, rid=None,
               token_base=0, trace=None, tenant=None, hold_kv=False,
               kv_import=None):
        """Enqueue one request (requires a prior ``start()``); raises
        ``ValueError`` if it can never fit a slot. ``deadline_s`` is a
        per-request budget (seconds or a ``Deadline``), measured from
        submission so queue wait counts. Returns the ``Request`` handle.

        ``token_base=k`` is the FAILOVER RESUME contract: ``prompt``
        must be the original prompt plus the ``k`` tokens already
        emitted elsewhere, and ``max_new_tokens`` the REMAINING budget —
        sampling keys start at stream index ``k``, so the continuation
        is bit-identical to the uninterrupted run's (same engine seed,
        same rid). ``trace`` tags the request's dispatch spans and
        retire event with a telemetry trace id. ``tenant`` attributes
        the request's latency/token metrics to a tenant label (QoS is
        enforced ABOVE the engine — frontend quotas/WFQ, router typed
        rejections; the scheduler itself stays tenant-blind).

        ``hold_kv=True`` marks a disaggregated prefill (pages held for
        export at "ok" retirement); ``kv_import=<ticket id>`` seats the
        request onto a completed KV import at admission — see
        ``export_pages``/``import_kv_chunk``."""
        prompt = np.asarray(prompt).astype(np.int32).ravel()
        self._validate(prompt, max_new_tokens)
        if rid is None:
            rid = self._auto_rid
            self._auto_rid += 1
        elif isinstance(rid, int) and rid >= self._auto_rid:
            # keep auto rids strictly above every explicit rid seen, so
            # mixing the two can't alias different requests
            self._auto_rid = rid + 1
        deadline = (deadline_s if isinstance(deadline_s, Deadline)
                    else Deadline(deadline_s))
        req = Request(rid, prompt, max_new_tokens, deadline,
                      token_base=token_base, trace=trace, tenant=tenant,
                      hold_kv=hold_kv, kv_import=kv_import)
        self._queue.append(req)
        return req

    def has_work(self) -> bool:
        # the unconsumed in-flight segment counts as work: after the last
        # live request is aborted mid-pipeline the carry must still be
        # drained by one more step() — otherwise it leaks device buffers
        # and a later submit would consume a segment built on a dead mask
        return (bool(self._queue)
                or any(r is not None for r in self._slot_req)
                or getattr(self, "_inflight", None) is not None)

    def free_slots(self) -> int:
        return sum(r is None for r in self._slot_req)

    def active_requests(self) -> list:
        return [r for r in self._slot_req if r is not None]

    def queued_requests(self) -> list:
        return list(self._queue)

    def abort(self, rid, status="cancelled"):
        """Pull a request out of the queue or its slot (its partial tokens
        stay on the handle). Returns the ``Request`` or None if unknown /
        already finished."""
        for req in self._queue:
            if req.rid == rid:
                self._queue.remove(req)
                self._retire(req, status)
                return req
        for slot, req in enumerate(self._slot_req):
            if req is not None and req.rid == rid:
                self._retire(req, status, slot=slot)
                if self._inflight is not None:
                    # the in-flight segment still decodes this slot; its
                    # emissions are discarded at consume, but the next
                    # dispatch must be a sync turn from the repaired mask
                    self._dirty = True
                return req
        return None

    # ----------------------------------------------- failure isolation

    def _retire(self, req, status, finished=None, slot=None):
        if req.status != "pending":
            return  # already retired (e.g. timed out inside a bisected try)
        pages_held = 0
        if slot is not None:
            self._slot_req[slot] = None
            self._lengths[slot] = 1  # slot returns to the idle pool
            pages_held = len(self._slot_pages[slot])
            if status == "ok" and req.hold_kv and self._slot_pages[slot]:
                # disaggregated prefill: the slot's page grants (and
                # their refcounts) move to the export hold table instead
                # of the free list — quarantine/eviction cannot recycle
                # them while a transfer is (or may be) in flight
                pages, self._slot_pages[slot] = self._slot_pages[slot], []
                self._set_table_row(slot)
                self._kv_holds[req.rid] = {
                    "pages": pages,
                    "prefill_len": int(req.prompt.size),
                    "first_token": int(req.tokens[0]) if req.tokens
                    else None,
                }
            else:
                self._free_slot_pages(slot)
        req.status = status
        self._counts[status] = self._counts.get(status, 0) + 1
        if telemetry.enabled():
            _M_REQS.inc(status=status)
            if req.t_first is not None:
                # the request's KV footprint at the page granularity it
                # actually occupied (the pages the allocator just freed)
                # — only requests that were ADMITTED (prefilled into a
                # slot); a queue-expired request held no pages
                pages = (pages_held if pages_held else
                         -(-(req.prompt.size + len(req.tokens))
                           // self.page_size))
                _M_KV_REQ.observe(pages * self.page_size
                                  * self._kv_bytes_per_token)
            if req.t_first is not None and len(req.tokens) > 1:
                per_tok = ((time.monotonic() - req.t_first)
                           / (len(req.tokens) - 1))
                _M_TOK.observe(per_tok)
                if req.tenant is not None:
                    _M_TOK.observe(per_tok, tenant=str(req.tenant))
            if req.tenant is not None and req.tokens:
                # tenant-attributed emission total (labeled series only;
                # the unlabeled serving.tokens_total counts at emission)
                _M_TOKENS.inc(len(req.tokens), tenant=str(req.tenant))
            telemetry.trace_event("serving.retire", trace=req.trace,
                                  rid=req.rid, status=status,
                                  tokens=len(req.tokens))
        if finished is not None:
            finished.append(req)

    def _check_poison(self, items):
        """Consume the ``serving.engine_fault`` injection budget once per
        request (STICKY: the poison mark survives bisection retries so the
        same offender fails deterministically), then fail the dispatch if
        any member of this batch is poisoned."""
        for _, req in items:
            if not req.poison_checked:
                req.poison_checked = True
                try:
                    inject("serving.engine_fault")
                except InjectedFault:
                    req.poisoned = True
        bad = [req for _, req in items if req.poisoned]
        if bad:
            raise InjectedFault(
                f"injected engine fault for request {bad[0].rid}")

    def _isolate(self, group, dispatch, finished):
        """Poison-request isolation: run ``dispatch(sub)`` over the
        admission group, BISECTING on failure so one poison request cannot
        take down its co-batched peers — survivors are re-dispatched in
        smaller batches (page writes are idempotent: a replayed prefill
        rewrites the same slot pages), and the offender retires as
        ``"failed"`` (``serving.poison_request`` in the ledger) instead of
        raising out of the scheduler with every in-flight slot lost."""
        group = [it for it in group if it[1].status == "pending"]
        if not group:
            return
        try:
            self._check_poison(group)
            dispatch(group)
            return
        except Exception as e:  # isolation boundary: bisect, never crash
            if len(group) == 1:
                slot, req = group[0]
                bump_counter("serving.poison_request")
                req.error = e
                # a poison retirement is a post-mortem moment: dump the
                # flight recorder so the offender leaves forensics
                telemetry.flight_dump("poison_request", rid=req.rid,
                                      error=repr(e))
                # slot= releases the admission's page grants even though
                # the request never registered in _slot_req (the dynamic
                # pool must not leak a failed admission's pages)
                self._retire(req, "failed", finished, slot=slot)
                return
        mid = len(group) // 2
        self._isolate(group[:mid], dispatch, finished)
        self._isolate(group[mid:], dispatch, finished)

    # ------------------------------------------------------- dispatches

    @staticmethod
    def _group_trace_args(group):
        """Span args for a batched admission dispatch: the rids (and any
        trace ids) riding it, so a per-request timeline can find the
        shared prefill span. Empty when telemetry is off — the lists are
        never built on a disabled hot path."""
        if not telemetry.enabled():
            return {}
        return {"rids": [req.rid for _, req in group],
                "traces": [req.trace for _, req in group
                           if req.trace is not None]}

    def _mask_trace_args(self, mask):
        """Span args for a decode-segment dispatch over the slot mask."""
        if not telemetry.enabled():
            return {}
        reqs = [self._slot_req[s] for s in np.flatnonzero(mask)]
        return {"rids": [r.rid for r in reqs if r is not None],
                "traces": [r.trace for r in reqs
                           if r is not None and r.trace is not None]}

    def _limits_device(self):
        if self._limits_dev is None:
            self._limits_dev = jnp.asarray(self._limits)
        return self._limits_dev

    def _finish_admit(self, slot, req, tok, finished):
        """Shared post-prefill bookkeeping (short, chunked AND
        prefix-resume paths): register the slot, count the sampled
        token, set the per-slot budget, insert the prompt's full pages
        into the prefix cache, and retire immediately on eos /
        exhausted budget. A PREEMPTED request re-admits here with its
        partial output folded into the prompt — ``len(req.tokens)``
        already counts those emissions, so the key stream, budget, and
        limit arithmetic stay globally indexed."""
        self._slot_req[slot] = req
        fresh_first = not req.tokens
        req.tokens.append(int(tok))
        self._useful += 1  # the prefill-sampled token
        if req.t_first is None:
            req.t_first = time.monotonic()
            if telemetry.enabled() and req.token_base == 0 and fresh_first:
                # FRESH attempts only: a failover continuation
                # (token_base > 0) emitted its real first token long ago
                # on another replica — an attempt-level sample here
                # would skew the fleet TTFT percentiles during exactly
                # the incidents where the SLO number matters
                _M_TTFT.observe(req.t_first - req.t_submit)
                if req.tenant is not None:
                    # per-tenant attribution SERIES (the unlabeled
                    # series above stays the total; these answer "whose
                    # latency" in fleet_metrics()['tenants'])
                    _M_TTFT.observe(req.t_first - req.t_submit,
                                    tenant=str(req.tenant))
        if telemetry.enabled():
            _M_TOKENS.inc()
        self._lengths[slot] = req.prompt.size
        self._cur_tok[slot] = int(tok)
        # final slot length: prompt + remaining emission budget - 1
        # (len(tokens) - 1 emissions happened in EARLIER attempts for a
        # preempted resume; for a fresh request this is the historical
        # prompt + max_new - 1)
        self._limits[slot] = (req.prompt.size + req.max_new_tokens
                              - len(req.tokens))
        self._limits_dev = None  # admission changed the device invariant
        if self._prefix is not None:
            # the slot's full prompt pages now hold valid KV: future
            # prompts sharing this prefix map them instead of
            # re-prefilling (refcounted — they outlive this request)
            self._prefix.insert(req.prompt, self._slot_pages[slot])
        if len(req.tokens) >= req.max_new_tokens or (
                self.eos_token_id is not None
                and req.tokens[-1] == self.eos_token_id):
            self._retire(req, "ok", finished, slot=slot)

    def _adopt_import(self, slot, req, imp, finished):
        """Seat a disaggregated-decode request directly onto imported
        prefill pages: pure host bookkeeping — page-table CONTENTS and
        scheduler state mutate, no program is traced or dispatched.

        Bit-exactness contract: the source replica sampled the prefill
        token (stream index 0 of the request's key stream, same engine
        seed + rid everywhere), so the adopted request starts with that
        token already in ``tokens`` and the next decode segment samples
        stream index ``token_base + len(tokens) == 1`` — identical to
        the colocated run's second token. TTFT was observed at the
        prefill; no attempt-level sample here."""
        meta = imp["meta"]
        plen = int(meta["prefill_len"])
        first = int(meta["first_token"])
        self._slot_pages[slot] = list(imp["pages"])
        self._set_table_row(slot)
        self._slot_adm[slot] = self._adm_seq
        self._adm_seq += 1
        self._slot_req[slot] = req
        req.tokens.append(first)
        if req.t_first is None:
            req.t_first = time.monotonic()
        self._lengths[slot] = plen
        self._cur_tok[slot] = first
        self._limits[slot] = (req.prompt.size + req.max_new_tokens
                              - len(req.tokens))
        self._limits_dev = None  # admission changed the device invariant
        if self._prefix is not None:
            self._prefix.insert(req.prompt, self._slot_pages[slot])
        bump_counter("serving.kv_import_adopted")
        if telemetry.enabled():
            telemetry.trace_event("serving.kv_adopt", trace=req.trace,
                                  rid=req.rid, pages=len(imp["pages"]))
        if len(req.tokens) >= req.max_new_tokens or (
                self.eos_token_id is not None
                and req.tokens[-1] == self.eos_token_id):
            self._retire(req, "ok", finished, slot=slot)

    def _dispatch_prefill(self, group, bucket, finished):
        # admission batch padded to the GROUP WIDTH (smallest power of two
        # >= the group, capped at max_slots): one compiled prefill shape
        # per (bucket x width), so a single admission pays a width-1
        # forward instead of a max_slots-wide one; padding rows write
        # scratch
        g = self._group_width(len(group))
        padded = np.zeros((g, bucket), np.int32)
        true_lens = np.ones((g,), np.int32)
        rows = np.full((g,), self.max_slots, np.int64)  # scratch
        for i, (slot, req) in enumerate(group):
            padded[i, :req.prompt.size] = req.prompt
            true_lens[i] = req.prompt.size
            rows[i] = slot
        t0 = time.monotonic()
        d = self._mark_dispatch()
        with annotate("serving.prefill", **self._group_trace_args(group)):
            tok0, self._ks, self._vs = self._call(
                ("prefill", bucket, g), self._prefill_p,
                self._params, self._ks, self._vs, jnp.asarray(padded),
                jnp.asarray(self._tables_np[rows]), jnp.asarray(true_lens),
                self._prefill_keys(group, g))
            tok0 = np.asarray(tok0)  # blocking fetch: the program ran
        self._mark_executed(d)
        if telemetry.enabled():
            perfwatch.observe_phase("prefill", time.monotonic() - t0)
        for i, (slot, req) in enumerate(group):
            self._finish_admit(slot, req, tok0[i], finished)

    def _dispatch_resume(self, group, bucket, finished):
        """PREFIX-RESUME admission dispatch: each row's shared prefix
        (``_resume_base`` tokens, keyed by request IDENTITY — rids are
        caller-supplied and may collide) is already mapped from the cache;
        only the divergent tail — padded to ``bucket`` — is written and
        the first token sampled at the true last position. Bases may sit
        mid-page (the CoW copy runs first, inside THIS isolation scope —
        a copy failure bisects like any admission failure), so the
        program uses the unaligned scatter write path."""
        pairs = [self._cow_pair[id(req)] for _, req in group
                 if id(req) in self._cow_pair]
        if pairs:
            self._dispatch_cow(pairs)
        g = self._group_width(len(group))
        padded = np.zeros((g, bucket), np.int32)
        bases = np.zeros((g,), np.int32)
        true_lens = np.ones((g,), np.int32)
        rows = np.full((g,), self.max_slots, np.int64)  # scratch
        for i, (slot, req) in enumerate(group):
            m = self._resume_base[id(req)]
            rem = req.prompt.size - m
            padded[i, :rem] = req.prompt[m:]
            bases[i] = m
            true_lens[i] = rem
            rows[i] = slot
        t0 = time.monotonic()
        d = self._mark_dispatch()
        with annotate("serving.prefill", **self._group_trace_args(group)):
            tok0, self._ks, self._vs = self._call(
                ("resume", bucket, g), self._resume_p,
                self._params, self._ks, self._vs, jnp.asarray(padded),
                jnp.asarray(self._tables_np[rows]), jnp.asarray(bases),
                jnp.asarray(true_lens), self._prefill_keys(group, g))
            tok0 = np.asarray(tok0)
        self._mark_executed(d)
        if telemetry.enabled():
            perfwatch.observe_phase("prefill", time.monotonic() - t0)
        for i, (slot, req) in enumerate(group):
            self._finish_admit(slot, req, tok0[i], finished)

    def _dispatch_cow(self, pairs):
        """Copy-on-write page copies, batched through the fixed-width
        ``("cow", _COW_WIDTH)`` program (padding lanes copy the dump page
        onto itself). Called from ``_dispatch_resume`` — INSIDE the
        ``_isolate`` boundary, before the group's prefill appends into
        the copies (device program order makes the copy visible) — so a
        device failure bisects like any admission failure, and a
        bisection replay harmlessly re-copies (the source is read-only
        shared content, the destination private)."""
        for i in range(0, len(pairs), _COW_WIDTH):
            batch = pairs[i:i + _COW_WIDTH]
            src = np.full((_COW_WIDTH,), self._dump_page, np.int32)
            dst = np.full((_COW_WIDTH,), self._dump_page, np.int32)
            for j, (s, t) in enumerate(batch):
                src[j] = s
                dst[j] = t
            self._mark_dispatch()
            self._ks, self._vs = self._call(
                ("cow", _COW_WIDTH), self._cow_p,
                self._params, self._ks, self._vs,
                jnp.asarray(src), jnp.asarray(dst))

    def _split_expired(self, items):
        live, expired = [], []
        for slot, req in items:
            if req.deadline.expired() or self._run_deadline.expired():
                expired.append((slot, req))
            else:
                live.append((slot, req))
        return live, expired

    def _chunked_prefill(self, group, finished):
        # CHUNKED PREFILL (long-context admission): full ``chunk_w``-token
        # chunks at per-row base offsets, then one padded final chunk that
        # also samples the first token. Rows are aligned by chunk index;
        # rows already past their full chunks ride the scratch page row.
        # A prefix-cache hit starts a row's chunks at its RESUME BASE
        # (the shared-prefix length, page-aligned for the bulk write
        # path) instead of 0 — the cached pages already hold that KV.
        # The request deadline is checked BETWEEN chunks: a long-context
        # admission whose budget expired mid-prefill retires as
        # ``timed_out`` without dispatching its remaining chunks.
        chunk_w = self.prompt_buckets[-1]
        scratch = self.max_slots
        start = {id(req): self._resume_base.get(id(req), 0)
                 for _, req in group}
        n_full = {id(req): (req.prompt.size - start[id(req)] - 1) // chunk_w
                  for _, req in group}
        live = list(group)
        expired = []
        c = 0
        while live:
            live, dead = self._split_expired(live)
            expired += dead
            if not live or not any(c < n_full[id(req)] for _, req in live):
                break
            g = self._group_width(len(live))
            chunk_arr = np.zeros((g, chunk_w), np.int32)
            bases = np.zeros((g,), np.int32)
            rows = np.full((g,), scratch, np.int64)
            for i, (slot, req) in enumerate(live):
                if c < n_full[id(req)]:
                    p = req.prompt
                    b0 = start[id(req)] + c * chunk_w
                    chunk_arr[i] = p[b0:b0 + chunk_w]
                    bases[i] = b0
                    rows[i] = slot
            t0 = time.monotonic()
            self._mark_dispatch()  # async: no fetch proves execution yet
            with annotate("serving.chunked_prefill",
                          **self._group_trace_args(live)):
                self._ks, self._vs = self._call(
                    ("chunk", g), self._chunk_p,
                    self._params, self._ks, self._vs, jnp.asarray(chunk_arr),
                    jnp.asarray(self._tables_np[rows]), jnp.asarray(bases))
            if telemetry.enabled():
                perfwatch.observe_phase("chunked_prefill",
                                        time.monotonic() - t0)
            c += 1
        if live:
            g = self._group_width(len(live))
            final_arr = np.zeros((g, chunk_w), np.int32)
            bases = np.zeros((g,), np.int32)
            true_rem = np.ones((g,), np.int32)
            rows = np.full((g,), scratch, np.int64)
            for i, (slot, req) in enumerate(live):
                p = req.prompt
                done = start[id(req)] + n_full[id(req)] * chunk_w
                rem = p.size - done
                final_arr[i, :rem] = p[done:]
                bases[i] = done
                true_rem[i] = rem
                rows[i] = slot
            t0 = time.monotonic()
            d = self._mark_dispatch()
            with annotate("serving.chunked_prefill",
                          **self._group_trace_args(live)):
                tok0, self._ks, self._vs = self._call(
                    ("final", g), self._final_chunk_p,
                    self._params, self._ks, self._vs, jnp.asarray(final_arr),
                    jnp.asarray(self._tables_np[rows]), jnp.asarray(bases),
                    jnp.asarray(true_rem), self._prefill_keys(live, g))
                tok0 = np.asarray(tok0)  # blocking fetch
            self._mark_executed(d)
            if telemetry.enabled():
                perfwatch.observe_phase("chunked_prefill",
                                        time.monotonic() - t0)
            for i, (slot, req) in enumerate(live):
                self._finish_admit(slot, req, tok0[i], finished)
        for slot, req in expired:
            # slot= so the admission's page grants return to the pool
            # (the request never registered in _slot_req)
            self._retire(req, "timed_out", finished, slot=slot)

    def _dispatch_segment(self, mask, carry=None, key_offset=0):
        """Dispatch ONE compiled decode segment (async — no host wait).

        ``carry=None`` is a SYNC dispatch from host state; otherwise
        ``carry`` is the previous segment's device outputs
        ``(tok, lengths, active)`` fed straight back as operands — the
        speculative pipelined turn, which costs no host round trip.
        Returns the in-flight handle consumed later by ``_consume``."""
        now = time.monotonic()
        if self._t_host0 is not None:
            gap = now - self._t_host0
            self._gap_sum += gap
            self._gap_n += 1
            self._t_host0 = None
            if telemetry.enabled():
                perfwatch.observe_phase("host_gap", gap)
        keys = self._segment_keys(key_offset)
        if carry is None:
            toks = jnp.asarray(self._cur_tok)
            lengths = jnp.asarray(self._lengths)
            active = jnp.asarray(mask)
        else:
            toks, lengths, active = carry
        d = self._mark_dispatch()
        with annotate("serving.segment_dispatch",
                      **self._mask_trace_args(mask)):
            emitted, was_active, tok, new_lengths, still_active, \
                self._ks, self._vs = self._call(
                    ("segment", self._segment_len), self._segment_p,
                    self._params, self._ks, self._vs,
                    self._tables_device(),
                    lengths, toks, active, self._limits_device(), keys)
        self._seg_runs += 1
        if self._megakernel and telemetry.enabled():
            _M_MEGA_SEG.inc()
        if telemetry.enabled():
            # host-side issue cost only: the call returns while the
            # device still runs (async dispatch)
            perfwatch.observe_phase("segment_dispatch",
                                    time.monotonic() - now)
        return {"emitted": emitted, "was_active": was_active, "tok": tok,
                "lengths": new_lengths, "active": still_active,
                "mask": np.asarray(mask), "disp": d}

    def _consume(self, h, finished):
        """Fetch one dispatched segment's outputs (ONE host round trip for
        all of them) and do the host bookkeeping: mirror lengths/tokens,
        append emissions, retire finished slots."""
        t0 = time.monotonic()
        emitted, was_active, cur_tok, lengths, still_active = \
            jax.device_get((h["emitted"], h["was_active"], h["tok"],
                            h["lengths"], h["active"]))
        t1 = time.monotonic()
        # the blocking fetch proves this segment (and every program
        # dispatched before it) executed: quarantined page frees up to
        # its dispatch index are safe to recycle
        self._mark_executed(h["disp"])
        if telemetry.enabled():
            # the blocking fetch: device compute the pipeline did not
            # hide (plus transfer) — the device share of a decode step
            perfwatch.observe_phase("device_wait", t1 - t0)
        useful0 = self._useful
        with annotate("serving.host_bookkeeping"):
            # slots outside ``mask`` pass through the program unchanged, so
            # wholesale assignment composes across bisected sub-batches
            self._lengths = lengths.copy()
            self._cur_tok = cur_tok.copy()
            # slots freed while this segment was in flight (abort /
            # failover retirement) must stay at the idle length — the
            # device view still carries the dead request's advance, and
            # resurrecting it here would hand the next admission a slot
            # that lies about its occupancy
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    self._lengths[slot] = 1
            for slot in np.flatnonzero(h["mask"]):
                req = self._slot_req[slot]
                if req is None:
                    continue
                toks = req.tokens
                for s in range(self._segment_len):
                    if not was_active[s, slot] or len(toks) >= \
                            req.max_new_tokens:
                        break
                    toks.append(int(emitted[s, slot]))
                    self._useful += 1
                done = (len(toks) >= req.max_new_tokens
                        or (self.eos_token_id is not None
                            and toks and toks[-1] == self.eos_token_id)
                        or not bool(still_active[slot]))
                if done:
                    self._retire(req, "ok", finished, slot=slot)
        self._t_host0 = time.monotonic()
        if telemetry.enabled():
            if self._useful > useful0:
                # one bump per consumed segment, not per token
                _M_TOKENS.inc(self._useful - useful0)
            perfwatch.observe_phase("host_bookkeeping",
                                    self._t_host0 - t1)

    def _drain_pipeline(self, finished):
        """Consume the in-flight segment (if any) so the host view of
        slots/lengths is current — required before any admission, and
        before bisection replays. A segment whose async execution failed
        is replayed serially from the last synced host state so the
        bisection isolation still applies."""
        h, self._inflight = self._inflight, None
        self._dirty = False
        if h is None:
            return
        try:
            self._consume(h, finished)
        except Exception:  # isolation boundary: replay serially + bisect
            live = np.array([r is not None for r in self._slot_req])
            self._segment_round(h["mask"] & live, finished)

    def _segment_round(self, mask, finished):
        """One compiled decode segment over the slots in ``mask`` + host
        token collection — the SERIAL turn (dispatch, wait, consume). A
        dispatch failure bisects the ACTIVE MASK (the compiled shape is
        fixed, so isolation masks slots out rather than re-batching) until
        the offending slot is alone, then retires it as ``"failed"`` — its
        co-batched slots decode in the retried halves. Per-request key
        streams make the replayed halves token-identical to an unbisected
        run."""
        if not mask.any():
            return
        try:
            h = self._dispatch_segment(mask)
            self._consume(h, finished)
        except Exception as e:  # isolation boundary: bisect, never crash
            idx = np.flatnonzero(mask)
            if len(idx) == 1:
                slot = int(idx[0])
                req = self._slot_req[slot]
                bump_counter("serving.poison_request")
                req.error = e
                telemetry.flight_dump("poison_request", rid=req.rid,
                                      error=repr(e))
                self._retire(req, "failed", finished, slot=slot)
                return
            left = mask.copy()
            left[idx[len(idx) // 2:]] = False
            self._segment_round(left, finished)
            self._segment_round(mask & ~left, finished)

    def _pipelined_round(self, mask, finished):
        """One OVERLAPPED scheduler turn: dispatch the next segment before
        consuming the previous one, so the device computes segment N+1
        while the host does segment N's bookkeeping.

        The speculative dispatch feeds segment N's device outputs straight
        back as the carry — retirements the device itself decided (eos,
        token budget) ride the carried active mask, so no host sync is
        needed. Host-only mask changes (admission, abort, deadline) drain
        the pipeline first via ``step()``. Per-request key streams keep
        the speculative segment token-identical to the serial schedule."""
        prev = self._inflight
        if prev is None:
            try:
                self._inflight = self._dispatch_segment(mask)
            except Exception:
                # sync dispatch failed: fall back to the serial round,
                # which replays with bisection
                self._segment_round(mask, finished)
            return
        seg = self._segment_len
        # speculate only when some slot can outlive the in-flight segment
        # (absent eos): otherwise every masked slot retires when ``prev``
        # is consumed and the speculative segment would be pure waste
        spec_worthy = any(
            self._slot_req[s] is not None
            and len(self._slot_req[s].tokens) + seg
            < self._slot_req[s].max_new_tokens
            for s in np.flatnonzero(mask))
        if not spec_worthy:
            self._drain_pipeline(finished)
            return
        try:
            h = self._dispatch_segment(
                mask, carry=(prev["tok"], prev["lengths"], prev["active"]),
                key_offset=seg)
        except Exception:
            # the speculative dispatch failed before running: drain the
            # pipeline, then replay this segment serially with bisection
            self._drain_pipeline(finished)
            live = np.array([r is not None for r in self._slot_req])
            self._segment_round(mask & live, finished)
            return
        # h becomes the in-flight segment BEFORE prev's bookkeeping so
        # pages freed by retirements inside _consume see it and
        # quarantine (h still writes every carried slot's cell)
        self._inflight = h
        try:
            self._consume(prev, finished)
        except Exception:  # isolation boundary: bisect, never crash
            # prev's ASYNC execution failed (surfaced at the fetch, not
            # the dispatch): the speculative segment was built on its
            # outputs — discard it and replay prev's window serially from
            # the last synced host state, bisecting to isolate
            self._inflight = None
            live = np.array([r is not None for r in self._slot_req])
            self._segment_round(prev["mask"] & live, finished)
            return

    def step(self):
        """One scheduler turn: admit queued requests into free slots
        (same-bucket admissions share ONE compiled prefill dispatch at the
        group width, under poison isolation), run one compiled decode
        segment — overlapped with the previous segment's host bookkeeping
        when the pipeline is enabled — then enforce deadlines BETWEEN
        segments (never mid-dispatch). Returns the list of ``Request``
        objects retired this turn (one segment behind the device when
        pipelined)."""
        finished: list[Request] = []
        # admission and mask repair need a current host view: consume the
        # in-flight segment BEFORE touching slots (prefill rewrites a
        # freed slot's pages; the in-flight segment was built on the old
        # mask)
        if self._inflight is not None and (
                self._dirty or (self._queue and self.free_slots() > 0)):
            self._drain_pipeline(finished)
        # ---- admission: FIFO over the queue, bounded by free slots AND
        # free POOL PAGES. A head the pool cannot serve DEFERS the whole
        # queue (no skip-ahead — a stream of small requests must not
        # starve a big one) with serving.kv_pool_exhausted backpressure.
        self.admission_blocked = False
        self._resume_base = {}
        self._cow_pair = {}
        chunk_w = self.prompt_buckets[-1]
        free = [s for s in range(self.max_slots)
                if self._slot_req[s] is None]
        admitting, long_adm, resume_adm = [], [], []
        fi = 0
        while self._queue and fi < len(free):
            req = self._queue[0]
            if req.status != "pending":
                self._queue.popleft()
                continue
            if req.kv_import is not None:
                # disaggregated DECODE admission: adopt the completed KV
                # import — pure host bookkeeping, no prefill dispatch.
                # A missing/incomplete ticket (source died, chunks never
                # finished) falls through to a normal local re-prefill.
                imp = self._imports.pop(req.kv_import, None)
                req.kv_import = None
                if (imp is not None
                        and len(imp["done"]) >= int(
                            imp["meta"]["n_chunks"])
                        and int(imp["meta"]["prefill_len"])
                        == int(req.prompt.size)):
                    self._queue.popleft()
                    slot = free[fi]
                    fi += 1
                    self._adopt_import(slot, req, imp, finished)
                    continue
                if imp is not None:
                    self._recycle(self._pool.decref(imp["pages"]))
                bump_counter("serving.kv_import_miss")
            plan = self._plan_admission(req)
            if plan is None and self._quarantine:
                # the missing pages may be freed-but-unproven: block on
                # the pool buffers (proves every dispatched program
                # executed, draining the quarantine) and retry — without
                # this, a session whose only retirement rode a failed
                # dispatch could defer the head forever with no active
                # slot left to trigger the _ensure_pages flush
                jax.block_until_ready(self._ks[0])
                self._mark_executed(self._disp_n)
                plan = self._plan_admission(req)
            if plan is None:
                bump_counter("serving.kv_pool_exhausted")
                self.admission_blocked = True
                break
            self._queue.popleft()
            slot = free[fi]
            fi += 1
            shared, m, cow_src, newp = plan
            self._slot_pages[slot] = list(shared) + newp
            self._set_table_row(slot)
            self._slot_adm[slot] = self._adm_seq
            self._adm_seq += 1
            self._prefix_lookup_tokens += int(req.prompt.size)
            if m:
                self._prefix_hit_tokens += m
                self._resume_base[id(req)] = m
                if telemetry.enabled():
                    _M_PREFIX_SAVED.inc(m)
                if cow_src is not None:
                    # the divergent page: copy the cached content, then
                    # append into the private copy (dispatched inside
                    # the request's resume-group isolation scope)
                    self._cow_pair[id(req)] = (
                        cow_src, self._slot_pages[slot][len(shared)])
                if req.prompt.size - m <= chunk_w:
                    resume_adm.append((slot, req))
                else:
                    long_adm.append((slot, req))
            elif req.prompt.size > chunk_w:
                long_adm.append((slot, req))
            else:
                admitting.append((slot, req))
        by_bucket: dict[int, list] = {}
        for slot, req in admitting:
            b = _bucket(req.prompt.size, self.prompt_buckets)
            by_bucket.setdefault(b, []).append((slot, req))
        for bucket, grp in by_bucket.items():
            self._isolate(
                grp, lambda sub, b=bucket: self._dispatch_prefill(
                    sub, b, finished), finished)
        r_by_bucket: dict[int, list] = {}
        for slot, req in resume_adm:
            b = _bucket(req.prompt.size - self._resume_base[id(req)],
                        self.prompt_buckets)
            r_by_bucket.setdefault(b, []).append((slot, req))
        for bucket, grp in r_by_bucket.items():
            self._isolate(
                grp, lambda sub, b=bucket: self._dispatch_resume(
                    sub, b, finished), finished)
        if long_adm:
            self._isolate(
                long_adm, lambda sub: self._chunked_prefill(sub, finished),
                finished)
        if self._cow_pair:
            # every copy is dispatched (or its request terminally
            # retired) by now: release the plan-time source-page holds —
            # the isolates never raise, so this line is always reached
            self._recycle(self._pool.decref(
                [s for s, _ in self._cow_pair.values()]))
            self._cow_pair = {}
        # decode growth: every active slot must hold pages for the next
        # dispatch window BEFORE it is dispatched (may preempt under
        # pool pressure — never fails a running decode)
        self._ensure_pages(finished)

        active_np = np.array([r is not None for r in self._slot_req])
        if telemetry.enabled():
            self._kv_account(active_np)
            perfwatch.memory_watchdog().maybe_poll()
        if active_np.any():
            self._occ_sum += float(active_np.mean())
            self._occ_n += 1
            if self._pipeline:
                self._pipelined_round(active_np, finished)
            else:
                self._segment_round(active_np, finished)
        elif self._inflight is not None:
            # nothing live in the host view but a segment still in flight
            # (every slot retired at the last consume): drain it
            self._drain_pipeline(finished)

        # deadline enforcement BETWEEN segments: an expired slot retires
        # with its partial output and frees capacity for the queue; queued
        # requests whose budget ran out while waiting drain as timed_out;
        # a run-level timeout retires everything still unfinished
        retired_slot = False
        for slot in range(self.max_slots):
            req = self._slot_req[slot]
            if req is not None and (req.deadline.expired()
                                    or self._run_deadline.expired()):
                self._retire(req, "timed_out", finished, slot=slot)
                retired_slot = True
        if retired_slot and self._inflight is not None:
            # the device cannot see a deadline retirement: force a drain
            # + sync turn before the next dispatch
            self._dirty = True
        if self._queue:
            waiting: deque[Request] = deque()
            for req in self._queue:
                if req.status != "pending":
                    continue
                if req.deadline.expired() or self._run_deadline.expired():
                    self._retire(req, "timed_out", finished)
                else:
                    waiting.append(req)
            self._queue = waiting
        return finished

    # ------------------------------------------------ dynamic page pool

    def _growth_horizon(self) -> int:
        """Positions the next dispatch window may write past a slot's
        current length: one segment, or two when the pipeline may hold
        an unconsumed segment plus a speculative one."""
        return self._segment_len * (2 if self._pipeline else 1)

    def _plan_admission(self, req):
        """Page plan for admitting ``req``: match its prompt against the
        prefix cache, then reserve pool pages for the unshared part.
        Returns ``(shared_pages, resume_tokens, cow_src, new_pages)`` —
        commits pool references on success — or ``None`` when the pool
        (after LRU cache eviction) cannot cover the admission plus its
        first-window decode growth: the caller defers the queue head.
        A previously PREEMPTED request requires coverage of its FULL
        remaining budget, so it cannot thrash straight back out."""
        P = int(req.prompt.size)
        page = self.page_size
        chunk_w = self.prompt_buckets[-1]
        shared, m, cow_src = [], 0, None
        if self._prefix is not None and P > 1:
            pages, matched, partial = self._prefix.match(req.prompt)
            mtok = matched + (partial.r if partial is not None else 0)
            # never serve the WHOLE prompt from cache: the last token
            # must run through the model to produce sampling logits
            mtok = min(mtok, P - 1)
            if P - mtok > chunk_w:
                # long divergent tail rides the page-aligned chunked
                # path: round the resume base down to a page boundary
                # (drops at most page_size-1 shared tokens)
                mtok = (mtok // page) * page
            full = mtok // page
            shared = pages[:full]
            m = mtok
            if m % page:
                # resume base sits mid-page: the covering cached page is
                # mapped via copy-on-write (writers must not touch the
                # shared original)
                cow_src = pages[full] if full < len(pages) else partial.page
        total = -(-P // page)
        new_needed = total - len(shared)
        remaining = req.max_new_tokens - len(req.tokens)
        final_len = max(P + remaining - 1, P)
        want_tokens = (final_len if req.preempted
                       else min(P + self._growth_horizon(), final_len))
        check_needed = max(-(-want_tokens // page), total) - len(shared)
        if self._pool.available() < check_needed:
            if self._prefix is not None:
                excl = set(shared)
                if cow_src is not None:
                    excl.add(cow_src)
                self._prefix.evict(
                    check_needed - self._pool.available(), exclude=excl)
            if self._pool.available() < check_needed:
                return None
        for p in shared:
            self._pool.incref(p)
        if cow_src is not None:
            # hold the copy source until the CoW dispatch reads it
            self._pool.incref(cow_src)
        newp = self._pool.alloc(new_needed) if new_needed else []
        return shared, m, cow_src, newp

    def _ensure_pages(self, finished):
        """Grant every active slot the pages its next dispatch window
        can write (admission granted prompt coverage only; decode grows
        page by page). Under pool pressure: evict prefix-cache leaves
        first, flush the free-quarantine (draining the pipeline proves
        execution), and as a last resort PREEMPT the youngest slot back
        to the queue — its stream resumes bit-identically via the
        per-request key stream, and the prefix cache usually makes the
        re-prefill one page of work. A running decode never fails."""
        horizon = self._growth_horizon()
        while True:
            need = []
            for slot, req in enumerate(self._slot_req):
                if req is None:
                    continue
                tgt = min(int(self._lengths[slot]) + horizon,
                          int(self._limits[slot]))
                short = (-(-tgt // self.page_size)
                         - len(self._slot_pages[slot]))
                if short > 0:
                    need.append((slot, short))
            total = sum(n for _, n in need)
            if not total:
                return
            if self._pool.available() < total and self._prefix is not None:
                self._prefix.evict(total - self._pool.available())
            if self._pool.available() >= total:
                for slot, n in need:
                    self._slot_pages[slot].extend(self._pool.alloc(n))
                    self._set_table_row(slot)
                return
            if self._quarantine:
                # freed pages are waiting on execution proof: drain the
                # pipeline (a blocking fetch) — or block on the pool
                # buffers directly when nothing is in flight
                if self._inflight is not None:
                    self._dirty = True
                    self._drain_pipeline(finished)
                else:
                    jax.block_until_ready(self._ks[0])
                    self._mark_executed(self._disp_n)
                continue
            victims = [s for s, r in enumerate(self._slot_req)
                       if r is not None]
            if len(victims) <= 1:
                # arithmetically unreachable (pool >= pages of one full
                # sequence and a lone slot's own grants count against
                # its need), but never spin here
                return
            self._preempt(max(victims, key=lambda s: self._slot_adm[s]),
                          finished)

    def _preempt(self, slot, finished):
        """Pull the request off ``slot`` to free its pages, folding its
        emitted tokens into the prompt (the failover-resume shape: key
        stream indices are ``token_base + len(tokens)``, both unchanged,
        so the eventual continuation is bit-identical). The request goes
        back to the FRONT of the queue."""
        req = self._slot_req[slot]
        bump_counter("serving.kv_preempted")
        if self._inflight is not None:
            # the in-flight segment still decodes this slot; discard its
            # unconsumed emissions (regenerated identically later) and
            # sync the host view first
            self._dirty = True
            self._drain_pipeline(finished)
            if self._slot_req[slot] is not req or req.status != "pending":
                return  # retired while draining — pages already freed
        if req.tokens:
            req.prompt = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
        req.preempted = True
        self._slot_req[slot] = None
        self._lengths[slot] = 1
        self._free_slot_pages(slot)
        self._queue.appendleft(req)
        if telemetry.enabled():
            telemetry.trace_event("serving.kv_preempt", trace=req.trace,
                                  rid=req.rid, emitted=len(req.tokens))

    # -------------------------- KV page transfer (disaggregation handoff)
    #
    # Engine-side primitive surface for prefill/decode disaggregation:
    # the SOURCE mints a ticket over the pages a hold_kv prefill pinned
    # (export_kv), serves CRC-framed fixed-width chunks (transfer_chunk)
    # and releases the pin when the handoff completes (release_export);
    # the DESTINATION lands chunks idempotently by ticket id
    # (import_kv_chunk) and the adopting request seats onto the landed
    # pages at admission. The chunk programs are AOT-warmed — the whole
    # path dispatches zero post-warmup compiles. The transfer DRIVER
    # (retries, failover, journaling) lives in models/transfer.py and
    # the router; the engine only moves pages.

    def _pinned_pages(self) -> int:
        """Pool pages pinned by the transfer machinery (holds + live
        export tickets + partially imported chunks) — granted, but
        invisible to the slot table."""
        return (sum(len(h["pages"])
                    for h in getattr(self, "_kv_holds", {}).values())
                + sum(len(e["pages"])
                      for e in getattr(self, "_exports", {}).values())
                + sum(len(i["pages"])
                      for i in getattr(self, "_imports", {}).values()))

    def export_pages(self, rid):
        """Mint (or re-serve) the transfer ticket over the pages a
        ``hold_kv`` prefill retirement pinned for ``rid``. Idempotent by
        rid — a router re-drive after a crash gets the SAME ticket, so
        the destination's by-ticket dedup makes the whole handoff
        exactly-once. Returns the ticket dict, or None when the rid
        holds no exportable pages (never prefilled here, already
        released, or a respawned engine)."""
        tid = self._export_by_rid.get(rid)
        if tid is not None and tid in self._exports:
            return dict(self._exports[tid]["ticket"])
        hold = self._kv_holds.pop(rid, None)
        if hold is None or hold["first_token"] is None:
            return None
        tid = uuid.uuid4().hex
        n_pages = len(hold["pages"])
        ticket = {
            "ticket": tid,
            "rid": rid,
            "n_pages": n_pages,
            "chunk_pages": _XFER_WIDTH,
            "n_chunks": -(-n_pages // _XFER_WIDTH),
            "prefill_len": hold["prefill_len"],
            "first_token": hold["first_token"],
            "page_size": self.page_size,
        }
        self._exports[tid] = {"pages": hold["pages"], "ticket": ticket}
        self._export_by_rid[rid] = tid
        return dict(ticket)

    def transfer_chunk(self, ticket, idx):
        """SOURCE side: serve chunk ``idx`` of a live export as
        ``[n_valid, payk, payv, crc32]`` — payloads are host
        ``(layers, W, page, kv, hd)`` arrays, CRC framed over both.
        An unknown ticket raises typed ``ServingUnavailable``: the
        caller cannot distinguish a released ticket from a respawned
        source, and both mean the pages are gone — re-prefill."""
        try:
            inject("transfer.source_death")
        except InjectedFault as e:
            bump_counter("transfer.source_death")
            raise ServingUnavailable(
                f"injected source death mid-transfer ({ticket})") from e
        exp = self._exports.get(ticket)
        if exp is None:
            raise ServingUnavailable(
                f"unknown export ticket {ticket!r}: no pinned pages "
                "(released, or a respawned source process)")
        sel = exp["pages"][idx * _XFER_WIDTH:(idx + 1) * _XFER_WIDTH]
        if not sel:
            raise ValueError(
                f"chunk {idx} out of range for ticket {ticket!r}")
        pad = sel + [self._dump_page] * (_XFER_WIDTH - len(sel))
        self._ks, self._vs, payk, payv = self._call(
            ("export", _XFER_WIDTH), self._export_p, self._params,
            self._ks, self._vs, jnp.asarray(np.asarray(pad, np.int32)))
        payk = np.asarray(jax.device_get(payk))
        payv = np.asarray(jax.device_get(payv))
        crc = zlib.crc32(payv.tobytes(), zlib.crc32(payk.tobytes()))
        return [len(sel), payk, payv, crc]

    def release_export(self, ticket) -> bool:
        """SOURCE side: drop a finished (or abandoned) export's pin —
        the pages decref back toward the free list. Idempotent."""
        exp = self._exports.pop(ticket, None)
        if exp is None:
            return False
        self._export_by_rid.pop(exp["ticket"]["rid"], None)
        self._recycle(self._pool.decref(exp["pages"]))
        return True

    def import_kv_chunk(self, meta, idx, payk, payv, crc):
        """DESTINATION side: land one CRC-framed chunk of the export
        described by ``meta`` (the ticket dict). First chunk allocates
        the local page grants; chunks land idempotently by ticket id +
        index, so a resumed transfer replays duplicates harmlessly.
        Returns ``"done"`` when every chunk has landed, ``"ok"`` on a
        partial landing, ``"dup"`` for an already-landed index,
        ``"crc_mismatch"`` for a corrupt frame (caller re-sends), or
        ``"no_capacity"`` when the pool cannot grant the pages."""
        try:
            inject("transfer.import_fail")
        except InjectedFault:
            bump_counter("transfer.import_fail")
            raise
        tid = meta["ticket"]
        st = self._imports.get(tid)
        if st is None:
            n_pages = int(meta["n_pages"])
            pages = self._pool.alloc(n_pages)
            if pages is None and self._prefix is not None:
                # same pressure valve admission uses: evict unreferenced
                # prefix pages, then retry the grant
                self._prefix.evict(n_pages - self._pool.available())
                pages = self._pool.alloc(n_pages)
            if pages is None:
                bump_counter("serving.kv_pool_exhausted")
                return "no_capacity"
            st = {"pages": pages, "meta": dict(meta), "done": set()}
            self._imports[tid] = st
        idx = int(idx)
        n_chunks = int(st["meta"]["n_chunks"])
        if idx in st["done"]:
            return "done" if len(st["done"]) >= n_chunks else "dup"
        payk = np.asarray(payk)
        payv = np.asarray(payv)
        if zlib.crc32(payv.tobytes(),
                      zlib.crc32(payk.tobytes())) != int(crc):
            bump_counter("transfer.crc_mismatch")
            return "crc_mismatch"
        w = int(st["meta"].get("chunk_pages", _XFER_WIDTH))
        sel = st["pages"][idx * w:(idx + 1) * w]
        if not sel:
            raise ValueError(
                f"chunk {idx} out of range for ticket {tid!r}")
        pad = sel + [self._dump_page] * (_XFER_WIDTH - len(sel))
        self._ks, self._vs = self._call(
            ("import", _XFER_WIDTH), self._import_p, self._params,
            self._ks, self._vs, jnp.asarray(np.asarray(pad, np.int32)),
            jnp.asarray(payk), jnp.asarray(payv))
        st["done"].add(idx)
        return "done" if len(st["done"]) >= n_chunks else "ok"

    def drop_import(self, ticket) -> bool:
        """DESTINATION side: abandon a (possibly partial) import and
        free its local page grants. Idempotent."""
        st = self._imports.pop(ticket, None)
        if st is None:
            return False
        self._recycle(self._pool.decref(st["pages"]))
        return True

    def _kv_usage(self, active_idx):
        """ONE definition of the page-granular KV arithmetic (the gauges
        and ``kv_stats`` must never desynchronize): pool occupancy,
        bytes, and fragmentation — the allocated-but-unused TAIL of the
        pages granted to active slots (the dynamic-allocator waste; the
        static slot map's waste was every slot's whole unreached tail).
        Prefix accounting rides along: hit rate is shared prompt tokens
        over admitted prompt tokens for the session."""
        n = len(active_idx)
        slot_pages = getattr(self, "_slot_pages",
                             [[] for _ in range(self.max_slots)])
        if n:
            used = int(self._lengths[list(active_idx)]
                       .astype(np.int64).sum())
            # logical grants (shared pages count once per MAPPING): the
            # fragmentation denominator — per-slot tail waste is defined
            # against what each slot was granted
            pages = sum(len(slot_pages[int(s)]) for s in active_idx)
            # physical bytes (shared pages count ONCE): what the slots
            # actually occupy of the pool — under prefix sharing the
            # logical sum can exceed the pool, the byte gauge must not
            phys = len({p for s in active_idx
                        for p in slot_pages[int(s)]})
        else:
            used = pages = phys = 0
        cap_tokens = pages * self.page_size
        pool = getattr(self, "_pool", None)
        free = pool.available() if pool is not None else 0
        lookups = getattr(self, "_prefix_lookup_tokens", 0)
        hits = getattr(self, "_prefix_hit_tokens", 0)
        return {
            "bytes_in_use": (phys * self.page_size
                             * self._kv_bytes_per_token),
            "slot_occupancy": n / self.max_slots if self.max_slots else 0.0,
            "fragmentation_pct": (100.0 * (1.0 - used / cap_tokens)
                                  if cap_tokens else 0.0),
            "bytes_per_token": self._kv_bytes_per_token,
            "pages_total": self._pool_pages,
            "pages_free": free,
            "pages_granted": phys,
            "pages_pinned_export": self._pinned_pages(),
            "prefix_cached_pages": (len(self._prefix)
                                    if self._prefix is not None else 0),
            "prefix_hit_rate": (hits / lookups) if lookups else 0.0,
            "prefix_tokens_saved": hits,
            "max_len": self.max_len,
            "max_len_rounded_from": self._max_len_rounded_from,
            "page_size": self.page_size,
        }

    def _kv_account(self, active_np):
        """Refresh the logical KV-occupancy gauges from the host view of
        the slots (one segment behind the device when pipelined)."""
        u = self._kv_usage(np.flatnonzero(active_np))
        _M_KV_BYTES.set(u["bytes_in_use"])
        _M_KV_OCC.set(u["slot_occupancy"])
        _M_KV_FRAG.set(u["fragmentation_pct"])
        _M_KV_PAGES_FREE.set(u["pages_free"])
        _M_KV_PAGES_TOTAL.set(u["pages_total"])
        _M_KV_PINNED.set(u["pages_pinned_export"])
        _M_PREFIX_HIT.set(u["prefix_hit_rate"])
        for slot in range(self.max_slots):
            _M_KV_SLOT_PAGES.set(len(self._slot_pages[slot]),
                                 slot=slot)

    def kv_stats(self) -> dict:
        """Point-in-time KV accounting for THIS engine (the gauges are
        process-level and last-writer-wins across engines)."""
        return self._kv_usage(
            [s for s, r in enumerate(getattr(self, "_slot_req", ()))
             if r is not None])

    def note_rejection(self):
        """Count a frontend-level rejection in the session stats, so
        ``stats()['rejected']`` reflects the whole serving stack (the
        engine itself never rejects — admission control lives above)."""
        self._counts["rejected"] = self._counts.get("rejected", 0) + 1

    def stats(self):
        """Running session stats. ``tokens_per_sec`` is 0.0 for an empty
        or zero-duration session (never inf).

        ``tokens_per_sec`` is measured over the session WALL clock, so a
        cold session (no prior ``warmup()``) absorbs every first-shape
        compilation into the number — call ``warmup()`` first (or compare
        only warmed sessions) when reading it as device throughput.
        ``host_gap_ms`` is the mean host-side gap between finishing one
        segment's bookkeeping and issuing the next dispatch
        (``host_gap_total_s`` is the session total) — with the pipeline
        enabled this work overlaps device compute; a growing value flags
        host-overhead regressions either way.

        ``phases`` (perfwatch step-time attribution) summarizes the
        PROCESS-wide ``serving.phase_s`` histogram — p50/p95/p99 + mean
        per scheduler phase (prefill / chunked_prefill /
        segment_dispatch / device_wait / host_bookkeeping / host_gap);
        ``kv`` is this engine's logical KV occupancy (bytes at page
        granularity, slot occupancy, interior fragmentation). Both are
        empty with ``FLAGS_telemetry=0``."""
        dt = time.monotonic() - self._t0
        return {
            "phases": (perfwatch.phase_summaries()
                       if telemetry.enabled() else {}),
            "kv": self.kv_stats() if telemetry.enabled() else {},
            "tokens_per_sec": (self._useful / dt
                               if dt > 0 and self._useful else 0.0),
            "useful_tokens": self._useful,
            "segments": self._seg_runs,
            "mean_occupancy": (self._occ_sum / self._occ_n
                               if self._occ_n else 0.0),
            "wall_s": dt,
            "host_gap_ms": (1e3 * self._gap_sum / self._gap_n
                            if self._gap_n else 0.0),
            "host_gap_total_s": self._gap_sum,
            "pipelined": bool(getattr(self, "_pipeline", False)),
            "timed_out": self._counts.get("timed_out", 0),
            "failed": self._counts.get("failed", 0),
            "cancelled": self._counts.get("cancelled", 0),
            "rejected": self._counts.get("rejected", 0),
        }

    # ------------------------------------------------------------ host loop

    def run(self, prompts, max_new_tokens, segment=16,
            request_deadline_s=None, timeout_s=None):
        """Generate ``max_new_tokens`` for every prompt (list of 1-D int
        arrays, mixed lengths), admitting/retiring between ``segment``-step
        compiled decode windows. Returns (outputs, stats): outputs[i] is
        the generated id array for prompts[i]; stats carries sustained
        tokens/sec over the decode segments, occupancy, per-request
        ``statuses``, and ``timed_out``/``failed``/``cancelled``/
        ``rejected`` counts.

        Resilience budgets (checked BETWEEN segments, so a straggler
        never blocks in-flight slots mid-dispatch):

        * ``request_deadline_s`` — wall-clock budget per request (scalar,
          or a per-request sequence; None entries are unbounded), measured
          from ``run()`` entry so queue wait counts. A request past its
          deadline is retired with whatever tokens it produced and status
          ``"timed_out"`` — it stops pinning a slot, queued requests that
          expired before admission drain the same way, and a long-context
          admission expiring mid-prefill skips its remaining chunks.
        * ``timeout_s`` — budget for the whole call; on expiry every
          unfinished request retires as ``timed_out`` and run() returns.

        Failure isolation: an exception inside a prefill / chunked-prefill
        / decode dispatch bisects the batch (see ``_isolate``) — the
        offending request retires as ``"failed"`` with its partial tokens
        while its co-batched peers complete normally. Token streams are
        identical with the pipeline on or off, under bisection replays,
        and for any admission interleaving (per-request key streams).
        """
        prompts_np = [np.asarray(p).astype(np.int32).ravel()
                      for p in prompts]
        for p in prompts_np:
            # validate UP FRONT: a request that can never fit must raise
            # before any other request's work is dispatched
            self._validate(p, max_new_tokens)
        if request_deadline_s is None or not np.iterable(request_deadline_s):
            request_deadline_s = [request_deadline_s] * len(prompts)
        if len(request_deadline_s) != len(prompts):
            raise ValueError(
                f"request_deadline_s has {len(request_deadline_s)} entries "
                f"for {len(prompts)} prompts")
        self.start(segment=segment, run_deadline=Deadline(timeout_s))
        reqs = [self.submit(p, max_new_tokens, deadline_s=s, rid=i)
                for i, (p, s) in enumerate(
                    zip(prompts_np, request_deadline_s))]
        while self.has_work():
            self.step()
        stats = self.stats()
        stats["statuses"] = [r.status for r in reqs]
        return [r.output() for r in reqs], stats
