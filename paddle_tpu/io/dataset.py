"""Dataset types.

Analog of /root/reference/python/paddle/io/dataloader/dataset.py:
map-style ``Dataset`` (__getitem__/__len__), ``IterableDataset``,
``TensorDataset``, ``ComposeDataset``, ``ChainDataset``, ``ConcatDataset``,
``Subset`` and ``random_split``.
"""
from __future__ import annotations

import bisect

import numpy as np

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __getitem__")

    def __len__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __len__")


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            f"{type(self).__name__} must implement __iter__")

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    """Wrap a list of tensors; sample i is tuple(t[i] for t in tensors)."""

    def __init__(self, tensors):
        from ..core.tensor import Tensor

        if not tensors:
            raise ValueError("TensorDataset needs at least one tensor")
        self.tensors = [
            t if isinstance(t, Tensor) else None or t for t in tensors
        ]
        n = len(tensors[0])
        for t in tensors:
            if len(t) != n:
                raise ValueError("all tensors must have the same first dim")

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    """Zip datasets: sample i concatenates the fields of each dataset's i."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ComposeDataset needs at least one dataset")
        n = len(self.datasets[0])
        for d in self.datasets:
            if len(d) != n:
                raise ValueError("datasets must have equal lengths")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    """Concatenate iterable datasets, streamed in order."""

    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    """Concatenate map-style datasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    """Split into non-overlapping subsets (reference dataset.py random_split).
    ``lengths`` may be absolute sizes or fractions summing to 1."""
    n = len(dataset)
    if all(0.0 < l < 1.0 for l in lengths) or (
        any(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6
    ):
        sizes = [int(np.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != n:
        raise ValueError("sum of input lengths does not equal dataset length")
    rng = np.random.default_rng(generator)
    perm = rng.permutation(n).tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out


def _no_download(download):
    """Shared no-egress guard for dataset auto-download requests."""
    if download:
        raise RuntimeError(
            "this environment has no network egress; place the dataset "
            "archive locally and pass data_file=/path (download=False)"
        )


def _require_file(value, download, what="data_file"):
    """Datasets that can never auto-download here: raise the no-egress
    error for download=True, else demand the explicit path."""
    if value is None:
        if download:
            _no_download(True)
        raise ValueError(
            f"{what} is required (download=True is unavailable: no "
            "network egress)")
    return value
