"""paddle_tpu — a TPU-native deep learning framework.

A from-scratch framework with the capabilities of PaddlePaddle
(reference at /root/reference, blueprint in SURVEY.md), built idiomatically
on JAX/XLA/Pallas: eager mode is op-by-op dispatch to cached XLA
executables; compiled mode (`jit`) is whole-graph trace; distribution is
sharding over `jax` device meshes with XLA collectives over ICI/DCN.
"""
from __future__ import annotations

from .core import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Parameter,
    Place,
    TPUPlace,
    Tensor,
    bfloat16,
    bool_,
    complex64,
    complex128,
    device_count,
    enable_grad,
    float8_e4m3fn,
    float8_e5m2,
    float16,
    float32,
    float64,
    get_device,
    get_flags,
    get_rng_state,
    grad,
    int8,
    int16,
    int32,
    int64,
    is_compiled_with_tpu,
    is_grad_enabled,
    no_grad,
    seed,
    set_device,
    set_flags,
    set_rng_state,
    to_tensor,
    uint8,
)
from .core.dtype import dtype  # noqa: F401
from .core.selected_rows import SelectedRows  # noqa: F401

# Functional op surface (paddle.* functions) — generated from ops.yaml.
from .ops import *  # noqa: F401,F403
from .ops import __all__ as _ops_all

from . import amp  # noqa: F401
from . import audio  # noqa: F401
from . import autograd  # noqa: F401
from . import hub  # noqa: F401
from . import sysconfig  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import quantization  # noqa: F401
from . import sparse  # noqa: F401
from . import onnx  # noqa: F401
from . import static  # noqa: F401
from . import strings  # noqa: F401
from . import text  # noqa: F401
from . import utils  # noqa: F401
from . import version  # noqa: F401
from . import distributed  # noqa: F401
from . import device  # noqa: F401
from . import distribution  # noqa: F401
from . import linalg  # noqa: F401
from . import signal  # noqa: F401

# `from . import fft` would be skipped: ops* already bound the `fft` op
# function here, and importlib's fromlist handling sees the existing
# attribute. Import the submodule explicitly; the namespace wins (its
# __call__-equivalent lives at paddle.fft.fft, reference layout).
import importlib as _importlib

fft = _importlib.import_module(".fft", __name__)
from . import geometric  # noqa: F401
from . import hapi  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import models  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import vision  # noqa: F401

# paddle-compat aliases
from .ops import cast as as_type  # noqa: F401


from .hapi import Model  # noqa: F401
from .hapi import model as callbacks  # noqa: F401  (paddle.callbacks.*)
from .nn import LazyGuard  # noqa: F401


def flops(net, input_size=None, inputs=None, custom_ops=None,
          print_detail=False):
    from .hapi import flops as _flops

    return _flops(net, input_size, inputs, custom_ops, print_detail)


def rand(shape, dtype="float32"):
    from .ops import uniform

    return uniform(shape=shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype="float32"):
    from .ops import gaussian

    return gaussian(shape=shape, mean=0.0, std=1.0, dtype=dtype)


def empty(shape, dtype="float32"):
    from .ops import zeros

    return zeros(shape=shape, dtype=dtype)


def empty_like(x, dtype=None):
    from .ops import zeros_like

    return zeros_like(x, dtype=dtype)


def numel(x):
    return x.size


def shape(x):
    return x.shape


def is_tensor(x):
    return isinstance(x, Tensor)


def get_default_dtype():
    from .core.flags import flag

    return flag("FLAGS_default_dtype")


def set_default_dtype(d):
    from .core.dtype import convert_dtype

    set_flags({"FLAGS_default_dtype": convert_dtype(d).name})


def save(obj, path, **kwargs):
    from .framework.io import save as _save

    return _save(obj, path, **kwargs)


def load(path, **kwargs):
    from .framework.io import load as _load

    return _load(path, **kwargs)


def summary(layer, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary

    return _summary(layer, input_size, dtypes)


__version__ = "0.1.0"
__all__ = (
    list(_ops_all)
    + [
        "Tensor",
        "Parameter",
        "to_tensor",
        "seed",
        "no_grad",
        "enable_grad",
        "grad",
        "set_device",
        "get_device",
        "device_count",
        "rand",
        "randn",
        "empty",
        "empty_like",
        "nn",
        "optimizer",
        "io",
        "amp",
        "jit",
        "distributed",
        "vision",
        "metric",
        "save",
        "load",
        "autograd",
    ]
)


# -------------------- reference-compat surface (round-2 audit) --------------
from .nn.layer_base import ParamAttr  # noqa: E402,F401
from .distributed.parallel import DataParallel  # noqa: E402,F401
from .autograd import set_grad_enabled  # noqa: E402,F401
from .core.enforce import (  # noqa: E402,F401
    EnforceNotMet, InvalidArgumentError,
)
from .core.place import CUDAPinnedPlace, CUDAPlace  # noqa: E402,F401

bool = bool_  # noqa: A001 — reference exposes paddle.bool


def iinfo(dtype):
    import numpy as _np

    from .core.dtype import to_jax_dtype

    return _np.iinfo(_np.dtype(to_jax_dtype(dtype)))


def finfo(dtype):
    import numpy as _np

    from .core.dtype import to_jax_dtype

    return _np.finfo(_np.dtype(to_jax_dtype(dtype)))


_static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


def enable_static():
    """Reference static-graph mode toggle. Static execution here IS jit
    tracing (SURVEY.md §7: PIR/executors absorbed by XLA) — the switch only
    flips ``in_dynamic_mode`` for compatibility checks."""
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def disable_signal_handler():
    """No-op: no native signal handlers are installed (reference installs
    C++ fault handlers in libpaddle.so)."""


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    import numpy as _np

    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if sci_mode is not None:
        kwargs["suppress"] = not sci_mode
    _np.set_printoptions(**kwargs)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone Parameter factory (reference paddle.create_parameter):
    delegates to Layer.create_parameter so ParamAttr semantics
    (initializer/trainable/regularizer/lr/lazy mode) stay in one place."""
    from .nn import Layer as _Layer

    holder = _Layer()
    return holder.create_parameter(tuple(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (reference paddle.batch)."""

    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return gen


def get_cuda_rng_state():
    """CUDA-free build: no per-GPU generator states (TPU RNG is the
    stateless threefry root — see get_rng_state)."""
    return []


def set_cuda_rng_state(state):
    if state:
        raise RuntimeError("this build has no CUDA generators")


__all__ += [  # noqa: F405
    "ParamAttr", "DataParallel", "set_grad_enabled", "bool", "iinfo",
    "finfo", "in_dynamic_mode", "enable_static", "disable_static",
    "disable_signal_handler", "set_printoptions", "create_parameter",
    "batch", "get_cuda_rng_state", "set_cuda_rng_state",
    "EnforceNotMet", "InvalidArgumentError", "CUDAPlace", "CUDAPinnedPlace",
    "addmm_", "check_shape",
]


def check_shape(shape):
    """Reference paddle.check_shape (utils/layers_utils.py:474): every
    element must be a positive int (or a Tensor dim)."""
    if isinstance(shape, Tensor):
        return
    for d in list(shape):
        if isinstance(d, Tensor):
            continue
        if not isinstance(d, int):
            raise TypeError(
                f"shape elements must be int or Tensor, got {type(d)}")
        if d < 0:
            raise ValueError(
                f"All elements in shape must be positive, got {d}")


def addmm_(input, x, y, beta=1.0, alpha=1.0):
    out = addmm(input, x, y, beta=beta, alpha=alpha)  # noqa: F405
    input._value = out._value
    return input


Tensor.addmm_ = addmm_
