"""Elastic scale-in/out with re-rendezvous (VERDICT r2 missing-7; analog
of the reference's ElasticManager scale events,
fleet/elastic/manager.py _update_fault_tolerance:457)."""
import os
import textwrap
import time

from paddle_tpu.distributed.fleet.elastic import ElasticManager, ElasticStatus
from paddle_tpu.distributed.store import TCPStore


def _mk_store():
    s = TCPStore(is_master=True)
    return s


def test_scale_plan_events():
    store = _mk_store()
    joiner = None
    mgrs = []
    try:
        mgrs = [ElasticManager(store=store, rank=r, world_size=4,
                               heartbeat_interval=0.05, lease=0.5,
                               np_range=(2, 5)) for r in range(4)]
        for m in mgrs:
            m.start()
        time.sleep(0.2)
        lead = mgrs[0]
        status, world = lead.scale_plan()
        assert status == ElasticStatus.HOLD and world == 4

        # host 3 dies -> scale-in plan to 3 (>= np_min)
        mgrs[3].stop()
        time.sleep(0.8)
        status, world = lead.scale_plan()
        assert status == ElasticStatus.RESTART and world == 3, (status, world)
        gen = lead.re_rendezvous(world)
        assert gen == 1 and lead.world_size == 3
        assert lead.current_generation() == 1

        # a NEW host announces -> scale-out back toward np_max
        joiner = ElasticManager(store=store, rank=99, world_size=3,
                                np_range=(2, 5))
        joiner.announce_join()
        status, world = lead.scale_plan()
        assert status == ElasticStatus.RESTART and world == 4, (status, world)
        gen = lead.re_rendezvous(world)
        assert gen == 2 and lead.world_size == 4
        # joiners absorbed: no further scale-out pending
        status, world = lead.scale_plan()
        assert world <= 4
    finally:
        # beat threads hold the native store client: stop BEFORE close
        if joiner is not None:
            joiner.stop()
        for m in mgrs:
            m.stop()
        store.close()


def test_scale_plan_below_min_exits():
    store = _mk_store()
    try:
        m0 = ElasticManager(store=store, rank=0, world_size=4,
                            heartbeat_interval=0.05, lease=0.4,
                            np_range=(3, 4))
        m0.start()
        time.sleep(0.15)
        status, world = m0.scale_plan()  # only 1 of 4 alive, min 3
        assert status == ElasticStatus.EXIT
        m0.stop()
    finally:
        store.close()


WORKER = textwrap.dedent("""
    import os, sys, time
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    world = int(os.environ["PADDLE_TRAINERS_NUM"])
    gen = int(os.environ.get("PADDLE_ELASTIC_GENERATION", "0"))
    if gen == 0:
        if rank == 2:
            sys.exit(1)          # this host dies in generation 0
        time.sleep(3.0)          # survivors outlive the failure detection
        sys.exit(0)
    # generation 1: re-rendezvoused at the surviving world size
    assert world == 2, world
    print(f"gen{gen} rank={rank}/{world} ok", flush=True)
    sys.exit(0)
""")


def test_launch_scale_in_restart(tmp_path):
    from paddle_tpu.distributed.launch import launch

    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    rc = launch(str(script), nproc_per_node=3, max_restarts=2,
                elastic_np=(1, 3), log_dir=str(tmp_path / "logs"))
    assert rc == 0
    logs = "".join((tmp_path / "logs" / f"worker.{r}.log").read_text()
                   for r in range(2))
    assert "gen1 rank=0/2 ok" in logs and "gen1 rank=1/2 ok" in logs, logs


def test_joiner_heartbeat_survives_lease(monkeypatch=None):
    """A joiner must stay visible past the lease window (its slot is
    heartbeat-refreshed, not written once)."""
    store = _mk_store()
    lead = ElasticManager(store=store, rank=0, world_size=1,
                          heartbeat_interval=0.05, lease=0.3,
                          np_range=(1, 3))
    joiner = ElasticManager(store=store, rank=50, world_size=1,
                            heartbeat_interval=0.05, lease=0.3,
                            np_range=(1, 3))
    try:
        lead.start()
        joiner.announce_join()
        time.sleep(0.6)  # well past the lease: one-shot writes would expire
        status, world = lead.scale_plan()
        assert status == ElasticStatus.RESTART and world == 2, (status, world)
    finally:
        # beat threads hold the native store client: stop BEFORE close
        joiner.stop()
        lead.stop()
        store.close()
