"""paddle_tpu.distributed.launch — the process launcher.

Analog of /root/reference/python/paddle/distributed/launch/ (main.py:23,
controllers/collective.py, controllers/master.py): rendezvous via a KV
master, rank/env assignment (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_MASTER), per-worker process spawn with log capture, a watch loop
that tears the job down on failure and (optionally) restarts it — the
reference's elastic controller behavior.

The KV master is the native TCPStore (paddle_tpu/native/tcp_store.cpp);
workers use it for barrier/endpoint exchange, mirroring HTTPMaster/
ETCDMaster. On TPU pods each *process* drives one host's chips
(multi-controller jax), so nproc_per_node maps to hosts-per-node rather
than chips.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "Pod"]


class Pod:
    """One node's worker processes (reference launch/job/pod.py)."""

    def __init__(self, nprocs, entry, entry_args, master_endpoint, log_dir=None,
                 env=None):
        self.nprocs = nprocs
        self.entry = entry
        self.entry_args = entry_args
        self.master_endpoint = master_endpoint
        self.log_dir = log_dir
        self.base_env = env or {}
        self.procs: list[subprocess.Popen] = []
        self.log_files = []

    def start(self):
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
        for rank in range(self.nprocs):
            env = dict(os.environ)
            env.update(self.base_env)
            # workers run with sys.path[0] = script dir; keep the launcher's
            # cwd importable (the reference launcher inherits it via cwd)
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in (os.getcwd(), env.get("PYTHONPATH", "")) if p)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(self.nprocs),
                "PADDLE_MASTER": self.master_endpoint,
                "PADDLE_RANK_IN_NODE": str(rank),
                "PADDLE_LOCAL_SIZE": str(self.nprocs),
            })
            cmd = [sys.executable, self.entry, *self.entry_args]
            if self.log_dir:
                log = open(os.path.join(self.log_dir, f"worker.{rank}.log"),
                           "w")
                self.log_files.append(log)
                proc = subprocess.Popen(cmd, env=env, stdout=log, stderr=log)
            else:
                proc = subprocess.Popen(cmd, env=env)
            self.procs.append(proc)

    def poll(self):
        """None while running; else (rank, returncode) of first failure or
        (-1, 0) when all exited cleanly."""
        alive = False
        for rank, p in enumerate(self.procs):
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                return (rank, rc)
        return None if alive else (-1, 0)

    def stop(self, sig=signal.SIGTERM):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(sig)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        for f in self.log_files:
            f.close()
        self.log_files.clear()


def launch(entry, entry_args=(), nproc_per_node=1, master=None, log_dir=None,
           max_restarts=0, env=None):
    """Run ``entry`` as ``nproc_per_node`` ranked worker processes.

    Returns 0 on success. Reference flow (launch/main.py → CollectiveController
    → Pod): start a TCPStore master, spawn ranked workers, watch; on worker
    failure stop the pod and (if restarts remain) relaunch everyone —
    elastic manager semantics (fleet/elastic/manager.py ElasticManager:125).
    """
    from ..store import TCPStore

    store = None
    if master is None:
        store = TCPStore(is_master=True)
        master = f"127.0.0.1:{store.port}"

    restarts = 0
    try:
        while True:
            pod = Pod(nproc_per_node, entry, list(entry_args), master,
                      log_dir=log_dir, env=env)
            pod.start()
            while True:
                status = pod.poll()
                if status is None:
                    time.sleep(0.2)
                    continue
                rank, rc = status
                break
            if rc == 0:
                return 0
            pod.stop()
            if restarts >= max_restarts:
                print(f"[launch] worker {rank} failed with code {rc}; "
                      f"no restarts left", file=sys.stderr)
                return rc
            restarts += 1
            print(f"[launch] worker {rank} failed (code {rc}); restart "
                  f"{restarts}/{max_restarts}", file=sys.stderr)
    finally:
        if store is not None:
            store.close()
