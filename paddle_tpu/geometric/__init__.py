"""paddle_tpu.geometric — graph learning primitives.

Analog of /root/reference/python/paddle/geometric/ (message passing
send_u_recv/send_ue_recv, segment ops, sampling). Segment reductions map to
``jax.ops.segment_*`` (XLA scatter — the role of the reference's CUDA
segment kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _num_segments(segment_ids, n):
    if n is not None:
        return int(n)
    return int(jnp.max(_v(segment_ids))) + 1


def segment_sum(data, segment_ids, num_segments=None):
    out = jax.ops.segment_sum(_v(data), _v(segment_ids),
                              _num_segments(segment_ids, num_segments))
    return Tensor._from_value(out)


def segment_mean(data, segment_ids, num_segments=None):
    n = _num_segments(segment_ids, num_segments)
    s = jax.ops.segment_sum(_v(data), _v(segment_ids), n)
    cnt = jax.ops.segment_sum(jnp.ones(_v(data).shape[0]), _v(segment_ids), n)
    cnt = jnp.maximum(cnt, 1.0)
    return Tensor._from_value(s / cnt.reshape((-1,) + (1,) * (s.ndim - 1)))


def segment_max(data, segment_ids, num_segments=None):
    out = jax.ops.segment_max(_v(data), _v(segment_ids),
                              _num_segments(segment_ids, num_segments))
    return Tensor._from_value(out)


def segment_min(data, segment_ids, num_segments=None):
    out = jax.ops.segment_min(_v(data), _v(segment_ids),
                              _num_segments(segment_ids, num_segments))
    return Tensor._from_value(out)


_REDUCERS = {"sum": segment_sum, "mean": segment_mean,
             "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """Gather source-node features along edges, reduce at destinations
    (reference geometric/message_passing/send_recv.py)."""
    msgs = _v(x)[_v(src_index)]
    n = out_size or _v(x).shape[0]
    return _REDUCERS[reduce_op](Tensor._from_value(msgs), dst_index, n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """Node⊕edge message passing."""
    msgs = _v(x)[_v(src_index)]
    e = _v(y)
    if message_op == "add":
        msgs = msgs + e
    elif message_op == "mul":
        msgs = msgs * e
    elif message_op == "sub":
        msgs = msgs - e
    elif message_op == "div":
        msgs = msgs / e
    else:
        raise ValueError(f"unsupported message_op {message_op!r}")
    n = out_size or _v(x).shape[0]
    return _REDUCERS[reduce_op](Tensor._from_value(msgs), dst_index, n)


def send_uv(x, y, src_index, dst_index, message_op="add"):
    """Per-edge messages from both endpoints."""
    xs = _v(x)[_v(src_index)]
    yd = _v(y)[_v(dst_index)]
    if message_op == "add":
        out = xs + yd
    elif message_op == "mul":
        out = xs * yd
    elif message_op == "sub":
        out = xs - yd
    elif message_op == "div":
        out = xs / yd
    else:
        raise ValueError(f"unsupported message_op {message_op!r}")
    return Tensor._from_value(out)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None):
    """Uniform neighbor sampling over a CSC graph (reference
    geometric/sampling/neighbors.py over graph_sample_neighbors kernels).

    row: (E,) CSC row indices; colptr: (N+1,) offsets; input_nodes: (B,)
    nodes to sample for. Returns (out_neighbors, out_count[, out_eids]).
    Host-side numpy (graph sampling is an input-pipeline stage, like the
    reference's CPU kernel path).
    """
    import numpy as np

    row_np = np.asarray(_v(row))
    colptr_np = np.asarray(_v(colptr))
    nodes = np.asarray(_v(input_nodes))
    eids_np = np.asarray(_v(eids)) if eids is not None else None
    # reproducible under paddle.seed: derive from the framework RNG stream
    from ..core.random import numpy_rng

    rng = numpy_rng()

    out_n, out_c, out_e = [], [], []
    for n in nodes:
        lo, hi = int(colptr_np[n]), int(colptr_np[n + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(lo, hi)
        else:
            idx = lo + rng.choice(deg, sample_size, replace=False)
        out_n.append(row_np[idx])
        out_c.append(len(idx))
        if eids_np is not None:
            out_e.append(eids_np[idx])
    neighbors = Tensor(np.concatenate(out_n) if out_n else
                       np.zeros(0, row_np.dtype))
    counts = Tensor(np.asarray(out_c, np.int32))
    if return_eids:
        return neighbors, counts, Tensor(
            np.concatenate(out_e) if out_e else np.zeros(0, np.int64))
    return neighbors, counts


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None):
    """Compact global node ids to local ids (reference
    geometric/reindex.py): x = unique seed nodes, neighbors = sampled
    neighbor ids. Returns (reindexed_src, reindexed_dst, out_nodes)."""
    import numpy as np

    seeds = np.asarray(_v(x))
    nbrs = np.asarray(_v(neighbors))
    cnts = np.asarray(_v(count))

    out_nodes = list(seeds)
    mapping = {int(n): i for i, n in enumerate(seeds)}
    for n in nbrs:
        if int(n) not in mapping:
            mapping[int(n)] = len(out_nodes)
            out_nodes.append(n)
    reindexed_src = np.asarray([mapping[int(n)] for n in nbrs], np.int64)
    dst = np.repeat(np.arange(len(seeds)), cnts)
    return (Tensor(reindexed_src), Tensor(dst.astype(np.int64)),
            Tensor(np.asarray(out_nodes, np.int64)))


__all__ += ["sample_neighbors", "reindex_graph"]
