"""Resilience primitives: retry/backoff/deadline budgets + fault injection.

The reference hardens its distributed runtime in C++ (gloo/NCCL retry
loops, comm_task_manager watchdogs, TCPStore reconnect logic spread across
paddle/phi/core/distributed/). Here that machinery is one host-side module
shared by every layer that talks over a wire or a filesystem: the
coordination-KV p2p transport, TCPStore clients, distributed checkpoints,
RPC, and the serving engine.

Three pieces:

* ``RetryPolicy`` — exponential backoff with jitter, bounded by BOTH a
  max-attempt budget and an optional ``Deadline``. A retry never sleeps
  past the deadline; the last failure is re-raised (chained) when the
  budget runs out.
* ``Deadline`` — an absolute point in time that propagates through call
  chains (``remaining()`` / ``remaining_ms()`` / ``expired()``), so nested
  retries share one wall-clock budget instead of multiplying timeouts.
* ``CircuitBreaker`` — closed → open → half-open failure gate with a
  monotonic cool-down, for subsystems (the serving engine) where retrying
  a persistently-broken dependency only amplifies the outage: after
  ``failure_threshold`` consecutive failures callers fail fast until a
  half-open probe succeeds.
* **Deterministic fault injection** — ``inject(site)`` points compiled
  into the transport/checkpoint/store paths, toggled by
  ``FLAGS_fault_injection`` (e.g. ``kv_drop:2`` = fail the first two
  fetches at site ``kv_drop``; ``store_set:*`` = fail every one). Faults
  raise ``InjectedFault`` (a ``ConnectionError``), which every retry
  policy here treats as transient — so tests and chaos drills exercise
  the REAL recovery paths, not mocks.

Observability: module-level counters (``bump_counter``/``counters``)
record retries, injected faults, and swallowed-but-counted failures such
as leaked coordinator keys.
"""
from __future__ import annotations

import logging
import random
import threading
import time

from . import telemetry
from .flags import define_flag, flag

__all__ = [
    "RetryPolicy", "Deadline", "CircuitBreaker",
    "CommTimeoutError", "InjectedFault", "CheckpointCorruptionError",
    "PeerFailureError", "ServingUnavailable", "StaleLeaderError",
    "TenantQuotaExceeded",
    "inject", "fault_remaining", "reset_faults",
    "bump_counter", "get_counter", "counters", "reset_counters",
]

logger = logging.getLogger("paddle_tpu.resilience")

define_flag("FLAGS_fault_injection", "",
            "Deterministic fault-injection spec 'site:N[,site:N...]': the "
            "first N inject(site) calls raise InjectedFault ('site:*' = "
            "every call, bare 'site' = once). Empty disables injection.")
define_flag("FLAGS_retry_max_attempts", 5,
            "Default RetryPolicy attempt budget (total tries, not retries)")
define_flag("FLAGS_retry_base_delay", 0.05,
            "Default RetryPolicy first backoff delay in seconds")
define_flag("FLAGS_retry_max_delay", 2.0,
            "Default RetryPolicy backoff ceiling in seconds")
define_flag("FLAGS_comm_timeout_ms", 120_000,
            "Default deadline for coordination-KV p2p fetches (ms)")
define_flag("FLAGS_heartbeat_ttl", 6.0,
            "Seconds without a store heartbeat before a rank counts dead")
define_flag("FLAGS_gang_barrier_timeout", 600.0,
            "Seconds a gang_barrier waits for all ranks before giving up")


# ------------------------------------------------------------------ errors

class InjectedFault(ConnectionError):
    """Raised by ``inject(site)`` — a ConnectionError so every transport
    retry policy classifies it as transient."""


class CommTimeoutError(TimeoutError):
    """A point-to-point transfer exhausted its deadline/retry budget.
    Carries the coordination key and the (src, dst) pair so a wedged
    pipeline names the exact edge instead of hanging."""

    def __init__(self, message, key=None, src=None, dst=None):
        super().__init__(message)
        self.key = key
        self.src = src
        self.dst = dst


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint shard failed its recorded CRC32 on load."""


class ServingUnavailable(RuntimeError):
    """A serving replica refused work: its frontend is stopped/draining,
    its circuit breaker is open, or (cross-process) the addressed
    ``ReplicaServer`` is not registered on the callee. Raised instead of
    a generic RuntimeError so a router-side caller can classify it as
    replica-level unavailability (reroute) rather than a request-level
    bug — and so the RPC transport can re-raise it TYPED on the caller
    side (models/remote.py, distributed/rpc.py)."""


class StaleLeaderError(RuntimeError):
    """A fenced call from a DEPOSED fleet leader was rejected: the
    envelope's fencing token is lower than the highest one this replica
    has seen (``distributed/gang.py LeaderLease``). Deliberately NOT a
    ConnectionError/TimeoutError/ServingUnavailable: the replica is
    healthy — it is the CALLER that lost the leadership — so the router
    must classify it as "stand down now" (stop dispatching, the new
    leader owns every in-flight request) rather than as replica death,
    which would make the zombie leader fail the request over and
    double-dispatch it. Travels typed across the RPC wire
    (distributed/rpc.py) like the other resilience errors."""


class TenantQuotaExceeded(RuntimeError):
    """A tenant's token-budget quota is exhausted: admitting this
    request would push the tenant's OUTSTANDING token cost (queued +
    in-flight prompt and decode budgets) past its configured
    ``quota_tokens`` (``models/qos.py``). Raised at the fleet router's
    client surface (the one place a client talks to) so an over-quota
    tenant gets a TYPED verdict it can back off on — deliberately NOT a
    ConnectionError/TimeoutError: nothing is broken, the tenant is out
    of budget, and a transport retry would just burn the quota check
    again. Carries ``tenant`` so multi-tenant clients can tell whose
    budget tripped. Travels typed across the RPC wire
    (distributed/rpc.py) like the other resilience errors."""

    def __init__(self, message, tenant=None):
        super().__init__(message)
        self.tenant = tenant


class PeerFailureError(Exception):
    """A gang peer stopped heartbeating (or a gang barrier could not
    complete) — the job must stop collective work NOW, checkpoint, and
    exit for supervised restart instead of burning the full comm timeout.

    Deliberately NOT a RuntimeError/ConnectionError/TimeoutError: every
    transport retry policy classifies those as transient, and a dead
    peer is not transient — the error must escape retry loops unwrapped
    so the training loop's elastic handler sees it within one heartbeat
    lease. Carries the dead ``rank`` (None when the gang is broken but
    no single culprit is known, e.g. a barrier timeout with all
    heartbeats live) and the ``phase`` that was blocked."""

    def __init__(self, message, rank=None, phase=None):
        super().__init__(message)
        self.rank = rank
        self.phase = phase


# ------------------------------------------------------------------ deadline

class Deadline:
    """An absolute time budget. ``Deadline(None)`` never expires. Built on
    the MONOTONIC clock: deadlines are purely process-local, and an NTP
    step must not expire every in-flight budget (or stall a watchdog)."""

    def __init__(self, seconds=None):
        self.expires_at = (None if seconds is None
                           else time.monotonic() + seconds)

    @classmethod
    def after(cls, seconds):
        return cls(seconds)

    @classmethod
    def from_ms(cls, ms):
        return cls(None if ms is None else ms / 1000.0)

    @classmethod
    def never(cls):
        return cls(None)

    def remaining(self) -> float:
        if self.expires_at is None:
            return float("inf")
        return self.expires_at - time.monotonic()

    def remaining_ms(self) -> float:
        return self.remaining() * 1000.0

    def expired(self) -> bool:
        return self.remaining() <= 0

    def __repr__(self):
        if self.expires_at is None:
            return "Deadline(never)"
        return f"Deadline({self.remaining():.3f}s left)"


# ------------------------------------------------------------------ retry

class RetryPolicy:
    """Exponential backoff + jitter under attempt AND deadline budgets.

    ``call(fn, deadline=...)`` runs ``fn`` up to ``max_attempts`` times,
    sleeping ``min(base * 2**i, max_delay) * (1 + jitter*u)`` between
    tries, never past the deadline. Only ``retry_on`` exceptions are
    retried; anything else propagates immediately. Defaults come from
    FLAGS at construction time so chaos drills can retune globally.
    """

    def __init__(self, max_attempts=None, base_delay=None, max_delay=None,
                 jitter=0.5, retry_on=(ConnectionError, TimeoutError, OSError),
                 sleep=time.sleep, rng=None):
        self.max_attempts = int(max_attempts if max_attempts is not None
                                else flag("FLAGS_retry_max_attempts"))
        self.base_delay = float(base_delay if base_delay is not None
                                else flag("FLAGS_retry_base_delay"))
        self.max_delay = float(max_delay if max_delay is not None
                               else flag("FLAGS_retry_max_delay"))
        self.jitter = float(jitter)
        self.retry_on = tuple(retry_on)
        self._sleep = sleep
        self._rng = rng or random.Random()

    def delay(self, attempt: int) -> float:
        base = min(self.base_delay * (2 ** attempt), self.max_delay)
        return base * (1.0 + self.jitter * self._rng.random())

    def call(self, fn, *args, deadline: Deadline | None = None,
             describe: str = None, on_retry=None, **kwargs):
        deadline = deadline or Deadline.never()
        last_exc = None
        for attempt in range(max(self.max_attempts, 1)):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last_exc = e
                bump_counter("retries" if attempt + 1 < self.max_attempts
                             else "retry_budget_exhausted")
                if attempt + 1 >= self.max_attempts:
                    break
                pause = self.delay(attempt)
                if deadline.remaining() <= pause:
                    bump_counter("retry_deadline_exhausted")
                    break
                if on_retry is not None:
                    on_retry(attempt, e)
                logger.warning("retrying %s after %s (attempt %d/%d, "
                               "backoff %.3fs)", describe or fn, e,
                               attempt + 1, self.max_attempts, pause)
                self._sleep(pause)
        raise last_exc


# ------------------------------------------------------ circuit breaker

class CircuitBreaker:
    """Consecutive-failure gate: closed → open → half-open → closed.

    * **closed** — traffic flows; ``record_failure`` increments a
      consecutive-failure count, ``record_success`` resets it. Hitting
      ``failure_threshold`` trips the breaker open.
    * **open** — ``allow()`` returns False (callers fail fast) until
      ``cooldown_s`` elapses on the MONOTONIC clock (an NTP step must not
      half-open every tripped breaker at once).
    * **half-open** — after the cool-down, ``allow()`` admits up to
      ``half_open_max`` probe calls. One recorded success closes the
      breaker; one recorded failure re-opens it for a fresh cool-down.

    State transitions land in the resilience ledger as
    ``circuit_opened:{name}`` / ``circuit_half_open:{name}`` /
    ``circuit_closed:{name}``; ``state()`` is a non-consuming view (it
    advances open → half-open on cool-down expiry but never spends a
    probe slot), so health endpoints can poll it freely.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, name="circuit", failure_threshold=5, cooldown_s=30.0,
                 half_open_max=1, clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.half_open_max = int(half_open_max)
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0          # consecutive failures while closed
        self._opened_at = None
        self._probes = 0            # half-open probes admitted

    # -- internal: advance open -> half-open once the cool-down elapsed
    def _tick(self):
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = self.HALF_OPEN
            self._probes = 0
            bump_counter(f"circuit_half_open:{self.name}")

    def state(self) -> str:
        with self._lock:
            self._tick()
            return self._state

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    def allow(self) -> bool:
        """May a call proceed right now? In half-open state each True
        consumes one of the ``half_open_max`` probe slots."""
        with self._lock:
            self._tick()
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                return False
            if self._probes < self.half_open_max:
                self._probes += 1
                return True
            return False

    def release_probe(self):
        """Return a half-open probe slot consumed by ``allow()`` when the
        probe resolved with NO verdict on the dependency (cancelled, timed
        out on its own budget) — another probe may then be admitted
        instead of the breaker waiting forever for an outcome."""
        with self._lock:
            if self._state == self.HALF_OPEN and self._probes > 0:
                self._probes -= 1

    def record_success(self):
        with self._lock:
            self._tick()
            if self._state == self.OPEN:
                # a late success from work admitted BEFORE the trip must
                # not cut the cool-down short; only a half-open probe can
                # close the breaker
                return
            if self._state == self.HALF_OPEN:
                if self._probes == 0:
                    # half-open but NO probe admitted yet: this success is
                    # stale pre-trip work arriving after the cool-down,
                    # not evidence from a probe
                    return
                bump_counter(f"circuit_closed:{self.name}")
                logger.info("circuit %r closed after successful probe",
                            self.name)
            self._state = self.CLOSED
            self._failures = 0
            self._probes = 0

    def trip(self):
        """Force the breaker open NOW, regardless of the consecutive-
        failure count — for callers holding out-of-band evidence the
        dependency is down (a serving router seeing a replica's gang
        heartbeat lapse does not need ``failure_threshold`` failed
        requests to stop routing there). Recovery is the normal path: the
        cool-down elapses, a half-open probe succeeds, the breaker
        closes."""
        with self._lock:
            tripped = self._state != self.OPEN
            if tripped:
                self._trip()
        if tripped:
            self._dump_trip()

    def record_failure(self):
        tripped = False
        with self._lock:
            self._tick()
            if self._state == self.HALF_OPEN:
                if self._probes == 0:
                    # stale pre-trip failure arriving after the cool-down:
                    # not probe evidence (mirror of record_success)
                    return
                self._trip()  # failed probe: fresh cool-down
                tripped = True
            elif self._state == self.OPEN:
                return
            else:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._trip()
                    tripped = True
        if tripped:
            self._dump_trip()

    def _trip(self):
        self._state = self.OPEN
        self._opened_at = self._clock()
        self._failures = 0
        self._probes = 0
        bump_counter(f"circuit_opened:{self.name}")
        logger.warning("circuit %r opened (cool-down %.3fs)",
                       self.name, self.cooldown_s)

    def _dump_trip(self):
        """A tripped breaker is a post-mortem moment: dump the flight
        recorder so the trip leaves WHY-context (the recent event ring
        includes whatever death/failure evidence preceded it), capped
        per process so a flapping breaker can't fill the disk. Runs
        AFTER the breaker lock is released — the dump does file I/O,
        and every allow()/record_* caller would stall on the lock for
        its duration."""
        telemetry.flight_recorder().record("circuit_opened",
                                           breaker=self.name)
        telemetry.flight_recorder().dump(f"breaker_trip:{self.name}")

    def __repr__(self):
        return (f"CircuitBreaker({self.name!r}, state={self.state()!r}, "
                f"threshold={self.failure_threshold})")


# ------------------------------------------------------- fault injection

_fault_lock = threading.RLock()
_fault_raw: str | None = None
_fault_remaining: dict[str, float] = {}


def _parse_spec(raw: str) -> dict[str, float]:
    out: dict[str, float] = {}
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        site, _, n = part.partition(":")
        n = n.strip()
        out[site.strip()] = (float("inf") if n in ("*", "inf")
                             else int(n) if n else 1)
    return out


def _sync_faults():
    global _fault_raw
    raw = flag("FLAGS_fault_injection")
    if raw != _fault_raw:
        _fault_raw = raw
        _fault_remaining.clear()
        _fault_remaining.update(_parse_spec(raw))


def inject(site: str):
    """Fault-injection point: raise ``InjectedFault`` while the site's
    FLAGS_fault_injection budget lasts, else no-op. Re-arming requires the
    flag VALUE to change (set it to '' between drills)."""
    with _fault_lock:
        _sync_faults()
        left = _fault_remaining.get(site, 0)
        if left <= 0:
            return
        _fault_remaining[site] = left - 1
        bump_counter(f"fault_injected:{site}")
        msg = (f"injected fault at site {site!r} "
               f"({_fault_remaining[site]} remaining)")
    raise InjectedFault(msg)


def fault_remaining(site: str) -> float:
    with _fault_lock:
        _sync_faults()
        return _fault_remaining.get(site, 0)


def reset_faults():
    """Disarm injection and forget consumed budgets (test teardown)."""
    from .flags import set_flags

    global _fault_raw
    with _fault_lock:
        set_flags({"FLAGS_fault_injection": ""})
        _fault_raw = None
        _fault_remaining.clear()


# ------------------------------------------------------------- counters
#
# Back-compat shim over the telemetry registry (core/telemetry.py):
# every resilience counter IS a registry Counter now, so the fleet
# metrics view (`ServingRouter.fleet_metrics()`), the Prometheus
# exposition, and the flight recorder all see the same ledger the
# historical ``bump_counter`` call sites feed — one source of truth.
# The surface (and every counter-name assertion in tests) is unchanged.

def bump_counter(name: str, n: int = 1) -> int:
    return telemetry.counter(name).inc(n)


def get_counter(name: str) -> int:
    return telemetry.counter(name).value()


def counters() -> dict[str, int]:
    """Every label-less counter series in the registry (the historical
    resilience ledger view; labeled telemetry series are visible in
    ``telemetry.registry().snapshot()``)."""
    out = {}
    for name, m in telemetry.registry().metrics().items():
        if m.kind != "counter":
            continue
        for key, v in m.series().items():
            if not key:
                out[name] = v
    return out


def reset_counters():
    """Zero every registry metric in place (test teardown). Cached
    metric handles stay registered and valid — only their series
    reset."""
    telemetry.registry().reset()
