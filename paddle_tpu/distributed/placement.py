"""Placement types: how one tensor dimension maps onto one mesh dimension.

Analog of the reference's C++ placement types
(/root/reference/paddle/phi/core/distributed/auto_parallel/placement_types.h
and python/paddle/distributed/auto_parallel/placement_type.py): a DistTensor
carries one Placement per mesh dimension — ``Shard(d)`` (tensor dim *d* is
split over that mesh dim), ``Replicate()`` (full copy on every device of
that mesh dim), or ``Partial(op)`` (each device holds an unreduced partial
term; a pending ``psum``).

TPU-native mapping: a placements list compiles to a
``jax.sharding.PartitionSpec`` — ``Shard(d)`` on mesh dim *i* puts that mesh
axis name at spec position *d*; ``Replicate`` contributes nothing. ``Partial``
has no on-device representation in a single-controller jax array (arrays are
always globally-consistent values); it exists transiently inside compiled
programs as an unreduced collective operand, and the placements metadata
records it so reshard semantics match the reference.
"""
from __future__ import annotations

__all__ = ["Placement", "Shard", "Replicate", "Partial"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self._dim = int(dim)

    def get_dim(self) -> int:
        return self._dim

    @property
    def dim(self) -> int:
        return self._dim

    def is_shard(self, dim=None):
        return dim is None or dim == self._dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other._dim == self._dim

    def __hash__(self):
        return hash(("Shard", self._dim))

    def __repr__(self):
        return f"Shard(dim={self._dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending reduction; ``reduce_type`` in {"sum", "avg", "max", "min"}."""

    def __init__(self, reduce_type: str = "sum"):
        if reduce_type not in ("sum", "avg", "max", "min"):
            raise ValueError(f"unsupported Partial reduce_type {reduce_type!r}")
        self._reduce_type = reduce_type

    @property
    def reduce_type(self) -> str:
        return self._reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and other._reduce_type == self._reduce_type

    def __hash__(self):
        return hash(("Partial", self._reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self._reduce_type!r})"
