"""text.viterbi_decode, distributed.auto_tuner, onnx.export surface."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_viterbi_decode_matches_bruteforce():
    from paddle_tpu.text import viterbi_decode

    rng = np.random.RandomState(0)
    B, S, T = 2, 5, 3
    emis = rng.rand(B, S, T).astype(np.float32)
    trans = rng.rand(T, T).astype(np.float32)
    scores, paths = viterbi_decode(
        paddle.to_tensor(emis), paddle.to_tensor(trans),
        include_bos_eos_tag=False)

    # brute force over all tag sequences
    import itertools

    for b in range(B):
        best, best_path = -1e9, None
        for seq in itertools.product(range(T), repeat=S):
            sc = emis[b, 0, seq[0]]
            for i in range(1, S):
                sc += trans[seq[i - 1], seq[i]] + emis[b, i, seq[i]]
            if sc > best:
                best, best_path = sc, seq
        np.testing.assert_allclose(float(scores._value[b]), best, rtol=1e-5)
        assert tuple(np.asarray(paths._value)[b].tolist()) == best_path


def test_viterbi_decoder_layer():
    from paddle_tpu.text import ViterbiDecoder

    trans = paddle.to_tensor(np.random.rand(5, 5).astype(np.float32))
    dec = ViterbiDecoder(trans, include_bos_eos_tag=True)
    pot = paddle.to_tensor(np.random.rand(2, 4, 3).astype(np.float32))
    scores, paths = dec(pot)
    assert scores.shape == [2] and paths.shape == [2, 4]


def test_auto_tuner_prunes_and_ranks():
    from paddle_tpu.distributed.auto_tuner import AutoTuner

    tuner = AutoTuner({
        "num_devices": 8,
        "model_cfg": {"hidden_size": 1024, "num_layers": 8,
                      "vocab_size": 32000, "seq_length": 1024,
                      "global_batch_size": 32},
        "hbm_bytes": 16e9,
    })
    assert tuner.space, "no feasible configs found"
    for c in tuner.space:
        assert c["dp_degree"] * c["mp_degree"] * c["pp_degree"] == 8
        assert tuner._memory_bytes(c) <= 16e9
    costs = [tuner.estimate_cost(c) for c in tuner.space]
    assert costs == sorted(costs)


def test_auto_tuner_tune_loop():
    from paddle_tpu.distributed.auto_tuner import AutoTuner

    tuner = AutoTuner({
        "num_devices": 4,
        "model_cfg": {"hidden_size": 512, "num_layers": 4,
                      "vocab_size": 1000, "seq_length": 256,
                      "global_batch_size": 16},
    })

    # pretend dp=4 is the fastest
    def trial(c):
        return 100.0 * c["dp_degree"] - 10 * c["pp_degree"]

    best = tuner.tune(trial, max_trials=10)
    assert best["dp_degree"] == max(
        c["dp_degree"] for c, _ in tuner.history)


def test_onnx_export_default_is_explicit_error(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    model = nn.Linear(4, 2)
    # no ONNX emitter exists in this environment: the default must say so
    # loudly, never silently relabel another format as ONNX
    with pytest.raises(RuntimeError, match="cannot emit ONNX"):
        paddle.onnx.export(model, str(tmp_path / "m"),
                           input_spec=[InputSpec([1, 4], "float32")])


def test_onnx_export_stablehlo_opt_in(tmp_path):
    import paddle_tpu.nn as nn
    from paddle_tpu.static import InputSpec

    model = nn.Linear(4, 2)
    paddle.onnx.export(model, str(tmp_path / "m"),
                       input_spec=[InputSpec([1, 4], "float32")],
                       export_format="stablehlo")
    loaded = paddle.jit.load(str(tmp_path / "m"))
    out = loaded(paddle.to_tensor(np.ones((1, 4), np.float32)))
    assert out.shape == [1, 2]


def test_auto_tuner_device_spec_table():
    """Per-device peak table (reference cluster.py:1414 analog): specs
    resolve by device kind, unknown kinds degrade to v5e, and tuner_cfg
    overrides win."""
    from paddle_tpu.distributed.auto_tuner import AutoTuner, device_spec

    assert device_spec("TPU v5p")[0] == 459e12
    assert device_spec("TPU v6 lite")[1] == 32e9
    assert device_spec("weird-part") == device_spec("v5e")

    t = AutoTuner({"num_devices": 8, "device_kind": "v5p",
                   "model_cfg": {"hidden_size": 256, "num_layers": 2,
                                 "vocab_size": 1000, "seq_length": 128,
                                 "global_batch_size": 8}})
    assert t.peak == 459e12 and t.hbm == 95e9
    t2 = AutoTuner({"num_devices": 8, "device_kind": "v5p",
                    "peak_flops": 1.0e12,
                    "model_cfg": {"hidden_size": 256, "num_layers": 2,
                                  "vocab_size": 1000, "seq_length": 128,
                                  "global_batch_size": 8}})
    assert t2.peak == 1.0e12  # explicit override wins
