"""paddle_tpu.native — C++ runtime components.

The TPU build keeps the *control plane* native, as the reference does
(SURVEY.md §2.10): TCPStore rendezvous (tcp_store.cpp ←
paddle/phi/core/distributed/store/tcp_store.h:121). Libraries are built on
first use with the system toolchain and cached beside the sources; callers
fall back to pure-python implementations when no compiler is available.
"""
from __future__ import annotations

import os
import subprocess
import threading

_here = os.path.dirname(os.path.abspath(__file__))
_build_lock = threading.Lock()


def _lib_path(name: str) -> str:
    return os.path.join(_here, f"lib{name}.so")


def build_library(name: str, sources: list[str] | None = None,
                  extra_flags: list[str] | None = None) -> str | None:
    """Compile ``name``.cpp into lib``name``.so (cached). Returns the path,
    or None if the toolchain is unavailable/compilation fails."""
    out = _lib_path(name)
    sources = sources or [os.path.join(_here, f"{name}.cpp")]
    with _build_lock:
        if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in sources
        ):
            return out
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               *(extra_flags or []), "-o", out, *sources]
        try:
            # blocking UNDER the build lock is the contract here: the
            # lock exists to serialize the one-time g++ build, and a
            # second caller MUST park until the .so exists (tpu-lint's
            # usual "snapshot then block" fix would race the compiler)
            # tpu-lint: disable=lock-blocking-call
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            import sys

            print(f"[paddle_tpu.native] build of {name} failed:\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return None
        return out


def load_library(name: str):
    """ctypes.CDLL for a native component, building it if needed."""
    import ctypes

    path = build_library(name)
    if path is None:
        return None
    return ctypes.CDLL(path)
