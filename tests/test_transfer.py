"""Prefill/decode disaggregation + fault-tolerant KV page transfer
(ISSUE 16).

Three layers of drills:

* The TRANSFER PRIMITIVE in isolation: export tickets are minted over
  pinned pages and are rid-idempotent; a manual export → transfer →
  import hop reproduces the colocated stream bit-identically; every
  drilled wire fault (``transfer.chunk_drop``, ``transfer.source_death``,
  ``transfer.import_fail``) resolves to the typed verdict the router
  keys its policy on, with zero page leaks on either side.
* The ROUTER POLICY plane in-process: role-aware dispatch (advisory —
  degraded fleets serve colocated), the handoff happy path with ZERO
  post-warmup compiles, source death → re-prefill, destination import
  faults → bounded budget → "failed" (never a hang), breaker trips →
  colocated fallback, a killed source sweeping its parked transfers,
  and the journaled HANDOFF record driving an exactly-once standby
  re-drive.
* The flagship CROSS-PROCESS drill: 1 prefill + 2 decode replica
  processes over real RPC; the prefill replica is SIGKILLed with a
  page transfer parked mid-handoff; zero requests are lost, every
  stream is bit-identical to the uninterrupted run, the fleet degrades
  to colocated serving, and the respawned rank rejoins and hands off
  again.
"""
import itertools
import os
import signal
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import resilience, telemetry
from paddle_tpu.core.flags import set_flags
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.gang import LeaderLease
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.jit import count_backend_compiles
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.frontend import ServingFrontend
from paddle_tpu.models.journal import RequestJournal
from paddle_tpu.models.remote import RPC_MASTER_ENV, RemoteFrontend
from paddle_tpu.models.router import ServingRouter, launch_fleet
from paddle_tpu.models.serving import ContinuousBatchingEngine
from paddle_tpu.models.transfer import (
    TransferDestError,
    TransferNoCapacity,
    TransferSourceError,
    transfer_pages,
)


@pytest.fixture(autouse=True)
def _clean_resilience():
    resilience.reset_faults()
    resilience.reset_counters()
    yield
    resilience.reset_faults()
    resilience.reset_counters()


_CFG = LlamaConfig(vocab_size=97, hidden_size=16, intermediate_size=32,
                   num_hidden_layers=1, num_attention_heads=2,
                   max_position_embeddings=128, tie_word_embeddings=True)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(_CFG)


def _frontend(model, role="both", max_slots=2, segment=4, seed=13):
    eng = ContinuousBatchingEngine(model, max_slots=max_slots, max_len=64,
                                   prompt_buckets=(8, 16), do_sample=True,
                                   temperature=0.9, seed=seed)
    return ServingFrontend(eng, max_queue=32, segment=segment,
                           breaker_threshold=50, role=role)


def _prompts(n, rng_seed=3, lo=4, hi=10):
    rng = np.random.RandomState(rng_seed)
    return [rng.randint(0, _CFG.vocab_size,
                        (int(rng.randint(lo, hi)),)).astype(np.int32)
            for _ in range(n)]


def _reference(model, prompts, rids, max_new):
    """Uninterrupted colocated run with the same rids — the bit-exact
    target every disaggregated/faulted stream must reproduce."""
    fe = _frontend(model)
    for rid, p in zip(rids, prompts):
        fe.submit(p, max_new_tokens=max_new, rid=rid)
    out = fe.results(wait=True)
    fe.shutdown()
    return {rid: out[rid].tokens for rid in rids}


def _prefill_hold(fe, prompt, rid):
    """Run the prefill leg the router dispatches: full prompt, exactly
    one token, pages held for export at retire."""
    fe.submit(prompt, max_new_tokens=1, rid=rid, hold_kv=True)
    res = fe.results(wait=True)[rid]
    assert res.status == "ok" and len(res.tokens) == 1
    return res.tokens[0]


def _hog_pool(fe, rids, max_new=40):
    """Fill a frontend's whole page pool with direct submissions (2
    slots x 1 page at this scale) and pump until the pool is empty —
    the deterministic way to park a handoff on backpressure."""
    for rid, p in zip(rids, _prompts(len(rids), rng_seed=77)):
        fe.submit(p, max_new_tokens=max_new, rid=rid)
    for _ in range(20):
        if fe.engine._pool.available() == 0:
            return
        fe.step()
    raise AssertionError("hogs never exhausted the destination pool")


# --------------------------------------------- the transfer primitive


def test_export_ticket_minting_and_rid_idempotence(model):
    """A hold_kv prefill pins its pages; export_pages mints ONE ticket
    per rid (re-serving it on replays — the exactly-once anchor), an
    unknown rid is a typed None, and release unpins. No page leaks."""
    fe = _frontend(model, role="prefill")
    first = _prefill_hold(fe, _prompts(1)[0], rid=5)
    eng = fe.engine
    assert eng._pinned_pages() > 0          # the hold outlives retire
    t1 = fe.export_pages(5)
    t2 = fe.export_pages(5)                 # a re-drive gets the SAME
    assert t1["ticket"] == t2["ticket"]     # ticket (dedup key)
    assert t1["rid"] == 5 and t1["first_token"] == first
    assert t1["n_pages"] >= 1
    assert t1["n_chunks"] == -(-t1["n_pages"] // t1["chunk_pages"])
    assert fe.export_pages(999) is None     # never prefilled here
    assert fe.release_export(t1["ticket"])
    assert not fe.release_export(t1["ticket"])   # idempotent
    assert eng._pinned_pages() == 0
    assert fe.export_pages(5) is None       # released means gone
    fe.shutdown()


def test_manual_hop_is_bit_identical_to_colocated(model):
    """export → transfer_pages → kv_import submit on a second frontend
    reproduces the colocated stream bit-identically: the source sampled
    stream index 0, the destination adopts the pages and samples stream
    index 1 onward under the same (seed, rid) key stream."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    p = _prompts(1)[0]
    ref = _reference(model, [p], [3], 6)
    _prefill_hold(src, p, rid=3)
    ticket = src.export_pages(3)
    done = transfer_pages(src, dst, ticket)
    assert done["ticket"] == ticket["ticket"]
    dst.submit(p, max_new_tokens=6, rid=3, token_base=0,
               kv_import=ticket["ticket"])
    out = dst.results(wait=True)[3]
    assert out.status == "ok"
    np.testing.assert_array_equal(out.tokens, ref[3])
    assert resilience.get_counter("serving.kv_import_adopted") == 1
    assert src.release_export(ticket["ticket"])
    assert src.engine._pinned_pages() == 0
    assert dst.engine._pinned_pages() == 0  # adopted pages freed at retire
    src.shutdown()
    dst.shutdown()


def test_chunk_drop_resumes_and_stays_bit_exact(model):
    """A dropped frame retries just that chunk; landed chunks dedup by
    (ticket, index) so the replay is idempotent and the adopted stream
    is still bit-identical."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    p = _prompts(1)[0]
    ref = _reference(model, [p], [4], 6)
    _prefill_hold(src, p, rid=4)
    ticket = src.export_pages(4)
    set_flags({"FLAGS_fault_injection": "transfer.chunk_drop:2"})
    transfer_pages(src, dst, ticket)        # survives both drops
    assert resilience.get_counter("transfer.chunk_drop") == 2
    assert telemetry.counter("fleet.transfer_resumed_chunks").value() == 2
    dst.submit(p, max_new_tokens=6, rid=4, token_base=0,
               kv_import=ticket["ticket"])
    out = dst.results(wait=True)[4]
    assert out.status == "ok"
    np.testing.assert_array_equal(out.tokens, ref[4])
    src.release_export(ticket["ticket"])
    src.shutdown()
    dst.shutdown()


def test_chunk_drop_budget_exhaustion_is_typed_and_leak_free(model):
    """A chunk that NEVER arrives exhausts the per-chunk retry budget
    into a typed TransferDestError — the driver can fail, it can never
    hang — and the destination's partial import is dropped. The source
    pages stay pinned, so a later retry still succeeds."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    _prefill_hold(src, _prompts(1)[0], rid=6)
    ticket = src.export_pages(6)
    set_flags({"FLAGS_fault_injection": "transfer.chunk_drop:1000"})
    with pytest.raises(TransferDestError):
        transfer_pages(src, dst, ticket, max_chunk_retries=1)
    assert dst.engine._imports == {}        # partial dropped, no leak
    assert dst.engine._pinned_pages() == 0
    resilience.reset_faults()
    transfer_pages(src, dst, ticket)        # pages survived the failure
    src.release_export(ticket["ticket"])
    dst.drop_import(ticket["ticket"])
    assert dst.engine._pinned_pages() == 0
    src.shutdown()
    dst.shutdown()


def test_source_death_is_typed_source_error(model):
    """A source lost mid-transfer — drilled kill or a respawned process
    that no longer knows the ticket — is ALWAYS the typed
    TransferSourceError verdict (re-prefill is the only recovery),
    never silent corruption; the destination partial is dropped."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    _prefill_hold(src, _prompts(1)[0], rid=8)
    ticket = src.export_pages(8)
    set_flags({"FLAGS_fault_injection": "transfer.source_death:1"})
    with pytest.raises(TransferSourceError):
        transfer_pages(src, dst, ticket)
    assert resilience.get_counter("transfer.source_death") == 1
    assert dst.engine._imports == {}
    resilience.reset_faults()
    # the respawned-source shape of the same loss: the ticket is gone
    src.release_export(ticket["ticket"])
    with pytest.raises(TransferSourceError):
        transfer_pages(src, dst, ticket)
    assert dst.engine._pinned_pages() == 0
    src.shutdown()
    dst.shutdown()


def test_import_fault_budget_is_typed_dest_error(model):
    """Destination-side import faults retry within the chunk budget and
    then raise the typed TransferDestError the router charges against
    its transfer budget."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    _prefill_hold(src, _prompts(1)[0], rid=9)
    ticket = src.export_pages(9)
    set_flags({"FLAGS_fault_injection": "transfer.import_fail:1000"})
    with pytest.raises(TransferDestError):
        transfer_pages(src, dst, ticket, max_chunk_retries=2)
    assert resilience.get_counter("transfer.import_fail") >= 3
    resilience.reset_faults()
    assert dst.engine._imports == {}
    assert dst.engine._pinned_pages() == 0
    src.release_export(ticket["ticket"])
    src.shutdown()
    dst.shutdown()


def test_pool_exhaustion_is_backpressure_not_failure(model):
    """A destination pool that cannot grant the pages right now raises
    TransferNoCapacity — transient backpressure the router parks on
    without charging the budget. The export stays pinned, and the same
    transfer succeeds once capacity frees."""
    src = _frontend(model, role="prefill")
    dst = _frontend(model, role="decode")
    p = _prompts(1)[0]
    ref = _reference(model, [p], [2], 6)
    _prefill_hold(src, p, rid=2)
    ticket = src.export_pages(2)
    _hog_pool(dst, rids=(900, 901), max_new=8)
    with pytest.raises(TransferNoCapacity):
        transfer_pages(src, dst, ticket)
    assert dst.engine._imports == {}        # nothing half-landed
    dst.results(wait=True)                  # hogs retire, pages free
    transfer_pages(src, dst, ticket)        # same ticket now lands
    dst.submit(p, max_new_tokens=6, rid=2, token_base=0,
               kv_import=ticket["ticket"])
    out = dst.results(wait=True)[2]
    assert out.status == "ok"
    np.testing.assert_array_equal(out.tokens, ref[2])
    src.release_export(ticket["ticket"])
    src.shutdown()
    dst.shutdown()


# ------------------------------------------- router policy, in-process


def test_disagg_fleet_bit_identical_zero_postwarmup_compiles(model):
    """The happy path: a prefill+decode fleet serves every request
    through the handoff with streams bit-identical to colocated serving
    and ZERO post-warmup compiles — the export/import chunk programs
    are part of the warmup set, and page adoption is pure host
    bookkeeping."""
    prompts = _prompts(4)
    ref = _reference(model, prompts, list(range(4)), 6)

    router = ServingRouter()
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    router.add_replica(fe_pre, warmup=True)
    router.add_replica(fe_dec, warmup=True)
    c = telemetry.counter("xla.compiles_total")
    serving0 = c.value(phase="serving")
    with count_backend_compiles() as compiles:
        rids = [router.submit(p, max_new_tokens=6) for p in prompts]
        res = router.results(wait=True, timeout_s=600)
    for rid in rids:
        assert res[rid].status == "ok", res[rid]
        np.testing.assert_array_equal(res[rid].tokens, ref[rid])
    assert compiles == [], \
        f"disaggregated serving compiled {len(compiles)} programs"
    assert c.value(phase="serving") == serving0
    assert resilience.get_counter("fleet.transfer_started") == 4
    assert resilience.get_counter("fleet.transfer_completed") == 4
    assert resilience.get_counter("serving.kv_import_adopted") == 4
    # completed handoffs leak nothing on either side
    assert fe_pre.engine._exports == {}
    assert fe_pre.engine._pinned_pages() == 0
    assert fe_dec.engine._pinned_pages() == 0
    router.shutdown()


def test_role_surface_and_colocated_degradation(model):
    """Roles are advisory: a fleet with no decode-capable replica (or a
    one-token budget that makes the hop pointless) serves colocated —
    roles degrade, they never exclude. The role rides health() and the
    fleet metrics roster."""
    eng = ContinuousBatchingEngine(model, max_slots=2, max_len=64,
                                   prompt_buckets=(8, 16), seed=13)
    with pytest.raises(ValueError):
        ServingFrontend(eng, role="shard")
    router = ServingRouter()
    fe_a = _frontend(model, role="prefill")
    fe_b = _frontend(model, role="prefill")
    assert fe_a.health()["role"] == "prefill"
    router.add_replica(fe_a)
    router.add_replica(fe_b)
    prompts = _prompts(3, rng_seed=5)
    ref = _reference(model, prompts, list(range(3)), 6)
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    res = router.results(wait=True, timeout_s=600)
    for rid in rids:
        assert res[rid].status == "ok"
        np.testing.assert_array_equal(res[rid].tokens, ref[rid])
    assert resilience.get_counter("fleet.transfer_started") == 0
    fm = router.fleet_metrics()
    assert {r["role"] for r in fm["replicas"].values()} == {"prefill"}
    assert fm["transfers_inflight"] == 0
    router.shutdown()

    # prefill+decode, but a ONE-token budget: the prefill leg IS the
    # whole request — no hop is minted
    router2 = ServingRouter()
    router2.add_replica(_frontend(model, role="prefill"))
    router2.add_replica(_frontend(model, role="decode"))
    rid = router2.submit(_prompts(1, rng_seed=6)[0], max_new_tokens=1)
    assert router2.results(wait=True, timeout_s=600)[rid].status == "ok"
    assert resilience.get_counter("fleet.transfer_started") == 0
    router2.shutdown()


def test_router_source_death_reprefills_bit_exact(model):
    """The source dies mid-transfer: the router abandons the hop and
    re-prefills from the journaled prefix — the client stream is still
    bit-identical and no pages leak anywhere."""
    prompts = _prompts(2, rng_seed=9)
    ref = _reference(model, prompts, list(range(2)), 6)
    router = ServingRouter()
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    router.add_replica(fe_pre)
    router.add_replica(fe_dec)
    set_flags({"FLAGS_fault_injection": "transfer.source_death:1"})
    rids = [router.submit(p, max_new_tokens=6) for p in prompts]
    res = router.results(wait=True, timeout_s=600)
    for rid in rids:
        assert res[rid].status == "ok", res[rid]
        np.testing.assert_array_equal(res[rid].tokens, ref[rid])
    assert resilience.get_counter("transfer.source_death") == 1
    assert resilience.get_counter("fleet.transfer_abandoned") >= 1
    assert fe_pre.engine._exports == {}
    assert fe_pre.engine._pinned_pages() == 0
    assert fe_dec.engine._pinned_pages() == 0
    router.shutdown()


def test_router_import_fault_budget_retires_failed_never_hangs(model):
    """A destination that keeps failing imports charges the bounded
    transfer budget; exhaustion retires the request "failed" — a
    handoff can degrade or fail, it can NEVER hang — and the abandoned
    export is released."""
    router = ServingRouter(breaker_threshold=50)  # keep the dest eligible
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    router.add_replica(fe_pre)
    router.add_replica(fe_dec)
    set_flags({"FLAGS_fault_injection": "transfer.import_fail:100000"})
    rid = router.submit(_prompts(1, rng_seed=11)[0], max_new_tokens=6)
    res = router.results(wait=True, timeout_s=600)
    assert res[rid].status == "failed"
    assert resilience.get_counter("fleet.transfer_budget_exhausted") == 1
    assert resilience.get_counter("fleet.transfer_failed") >= 3
    resilience.reset_faults()
    assert fe_pre.engine._exports == {}     # abandoned hop released its pin
    assert fe_pre.engine._pinned_pages() == 0
    assert fe_dec.engine._pinned_pages() == 0
    router.shutdown()


def test_router_breaker_trip_degrades_to_colocated(model):
    """Same fault, default breaker: the failing destination's breaker
    opens, the candidate pool empties, and the router abandons the hop
    into a COLOCATED re-prefill — the client still gets the bit-exact
    stream, degraded but served."""
    p = _prompts(1, rng_seed=13)[0]
    ref = _reference(model, [p], [0], 6)
    router = ServingRouter()                # breaker_threshold=3
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    router.add_replica(fe_pre)
    router.add_replica(fe_dec)
    set_flags({"FLAGS_fault_injection": "transfer.import_fail:100000"})
    rid = router.submit(p, max_new_tokens=6)
    res = router.results(wait=True, timeout_s=600)
    resilience.reset_faults()
    assert res[rid].status == "ok", res[rid]
    np.testing.assert_array_equal(res[rid].tokens, ref[0])
    assert resilience.get_counter("fleet.transfer_abandoned") >= 1
    assert fe_pre.engine._exports == {}
    assert fe_pre.engine._pinned_pages() == 0
    assert fe_dec.engine._pinned_pages() == 0
    router.shutdown()


def test_killed_source_sweeps_its_parked_transfers(model):
    """A handoff parked on destination backpressure belongs to its
    source: when the source replica is killed, the kill sweep abandons
    the parked hop (the pages died with the process) and the request
    re-prefills on the survivor — bit-exact, zero lost."""
    p = _prompts(1, rng_seed=15)[0]
    ref = _reference(model, [p], [0], 6)
    router = ServingRouter()
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    router.add_replica(fe_pre)
    router.add_replica(fe_dec)
    _hog_pool(fe_dec, rids=(900, 901))      # decode pool: zero free pages
    rid = router.submit(p, max_new_tokens=6)
    for _ in range(200):
        router.step()
        if router._transfers:
            break
    assert rid in router._transfers, "handoff never parked"
    assert resilience.get_counter("fleet.transfer_backpressure") >= 1
    router.fail_replica(0, reason="drill")  # the SOURCE dies
    assert router._transfers == {}          # sweep abandoned the hop
    assert resilience.get_counter("fleet.transfer_abandoned") == 1
    res = router.results(wait=True, timeout_s=600)
    assert res[rid].status == "ok", res[rid]
    np.testing.assert_array_equal(res[rid].tokens, ref[0])
    assert fe_dec.engine._pinned_pages() == 0
    router.shutdown()


# --------------------------------------------- journal + takeover


def test_journal_handoff_record_roundtrip(tmp_path):
    """HANDOFF is a first-class WAL record: durable before the decode
    dispatch acks, cleared by HANDOFF_DONE, replayed by recover() so a
    takeover knows exactly which hops were mid-flight."""
    j = RequestJournal(tmp_path, epoch=1)
    assert j.admit(5, [1, 2, 3], 8)
    assert not j.handoff(99, source=0, ticket={"ticket": "zz"})  # unknown
    assert j.handoff(5, source=0,
                     ticket={"ticket": "abc", "n_pages": 1, "n_chunks": 1,
                             "chunk_pages": 4, "rid": 5, "prefill_len": 3,
                             "first_token": 42, "page_size": 64},
                     first_token=42, prefill_len=3, dest=None)
    j.flush()
    rec = RequestJournal.recover(root=tmp_path, epoch=2)
    ho = rec.live_state()[5].get("handoff")
    assert ho is not None
    assert ho["source"] == 0 and ho["first_token"] == 42
    assert ho["ticket"]["ticket"] == "abc"
    assert rec.handoff_done(5)
    assert not rec.handoff_done(5)          # already cleared
    rec.flush()
    rec2 = RequestJournal.recover(root=tmp_path, epoch=3)
    assert rec2.live_state()[5].get("handoff") is None
    j.close()
    rec.close()
    rec2.close()


def test_takeover_redrives_parked_handoff_exactly_once(model, tmp_path):
    """The router crashes with a journaled handoff parked mid-transfer;
    the standby replays the WAL, re-drives the hop against the LIVE
    source — the rid-idempotent export re-serves the SAME ticket, the
    destination dedups by it — and the client stream completes
    bit-exact with the prefill adopted exactly once."""
    p = _prompts(1, rng_seed=17)[0]
    ref = _reference(model, [p], [0], 6)
    fe_pre = _frontend(model, role="prefill")
    fe_dec = _frontend(model, role="decode")
    active = ServingRouter(journal_root=str(tmp_path), fleet_prefix="xfr")
    active.add_replica(fe_pre)
    active.add_replica(fe_dec)
    _hog_pool(fe_dec, rids=(900, 901))
    rid = active.submit(p, max_new_tokens=6)
    for _ in range(200):
        active.step()
        if active._transfers:
            break
    assert rid in active._transfers, "handoff never parked"
    active._journal.close()                 # "crash": heap gone, WAL on disk

    store = TCPStore(is_master=True)
    standby = ServingRouter(
        standby=True, journal_root=str(tmp_path), fleet_prefix="xfr",
        leader_lease=LeaderLease(store, prefix="xfr", owner="standby",
                                 ttl=1.0, interval=0.1))
    standby.add_replica(fe_pre)             # same ids as the dead leader
    standby.add_replica(fe_dec)
    info = standby.take_over(timeout=30.0)
    assert info["resubmitted"] == 1
    assert resilience.get_counter("fleet.handoff_redriven") == 1
    res = standby.results(wait=True, timeout_s=600)
    assert res[rid].status == "ok", res[rid]
    np.testing.assert_array_equal(res[rid].tokens, ref[0])
    assert resilience.get_counter("serving.kv_import_adopted") == 1
    assert fe_pre.engine._exports == {}     # completed hop released its pin
    assert fe_pre.engine._pinned_pages() == 0
    assert fe_dec.engine._pinned_pages() == 0
    standby.shutdown()
    store.close()


# ------------------------------------- flagship: multi-process drill


_XFER_REPLICA_SCRIPT = """
import os

import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.models.frontend import ServingFrontend
from paddle_tpu.models.remote import replica_main
from paddle_tpu.models.serving import ContinuousBatchingEngine

CFG = LlamaConfig(vocab_size=97, hidden_size=16, intermediate_size=32,
                  num_hidden_layers=1, num_attention_heads=2,
                  max_position_embeddings=128, tie_word_embeddings=True)


def build():
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    paddle.seed(0)
    model = LlamaForCausalLM(CFG)
    eng = ContinuousBatchingEngine(model, max_slots=2, max_len=64,
                                   prompt_buckets=(8, 16), do_sample=True,
                                   temperature=0.9, seed=13)
    return ServingFrontend(eng, max_queue=32, segment=4,
                           breaker_threshold=50,
                           role="prefill" if rank == 0 else "decode")


if __name__ == "__main__":
    raise SystemExit(replica_main(build))
"""


def _stub(rank):
    return RemoteFrontend(f"replica{rank}", timeout=60.0,
                          health_timeout=10.0, retry_attempts=2,
                          resend_after=30.0, results_wait=0.1)


def _drill_lease():
    """Heartbeat lease for the multi-process kill drills, widened with
    the machine's load: on a loaded 1-core CI box the replica
    heartbeater can be descheduled for seconds, and a fixed 1.5s lease
    then expires a LIVE replica (spurious failover -> flaky drill). The
    kill itself is still detected promptly via the in-flight transport
    error; the lease is only the backstop."""
    try:
        load = os.getloadavg()[0]
    except OSError:  # pragma: no cover - platform without getloadavg
        load = 0.0
    return min(12.0, max(3.0, 2.0 * load))


def _reference_subprocess_safe(prompts, rids, max_new):
    """Uninterrupted reference run with the fleet's rids, on a fresh
    deterministic model (paddle.seed(0)) — the same weights the replica
    processes build."""
    paddle.seed(0)
    model = LlamaForCausalLM(_CFG)
    return _reference(model, prompts, rids, max_new)


def test_cross_process_disagg_kill_prefill_mid_transfer(tmp_path):
    """THE acceptance drill across real process boundaries: 1 prefill +
    2 decode replica PROCESSES over RPC; the prefill replica is
    SIGKILLed with page transfers parked mid-handoff (the decode pools
    are pinned full so the park is deterministic); zero requests are
    lost, every stream is bit-identical to the uninterrupted run, the
    fleet degrades to colocated serving on the decode survivors, and
    the respawned rank rejoins and hands off again."""
    script = tmp_path / "replica.py"
    script.write_text(textwrap.dedent(_XFER_REPLICA_SCRIPT))
    store = rpc.init_rpc("router", rank=0, world_size=4)
    endpoint = f"127.0.0.1:{store.port}"
    fleet_store = TCPStore(port=store.port)
    router = ServingRouter(store=fleet_store, lease=_drill_lease(),
                           heartbeat_interval=0.1, max_failovers=3)
    rc_box = {}
    supervisor = threading.Thread(
        target=lambda: rc_box.update(rc=launch_fleet(
            str(script), n_replicas=3, max_restarts=2,
            env={RPC_MASTER_ENV: endpoint},
            backoff_base=0.01, poll_interval=0.05)),
        daemon=True)
    supervisor.start()
    try:
        for rank in (0, 1, 2):
            rpc.get_worker_info(f"replica{rank}", timeout=300)
            router.add_replica(_stub(rank), replica_id=rank)
        assert router._replicas[0].role == "prefill"
        assert router._replicas[1].role == "decode"
        pids = {r: int(fleet_store.get(f"fleet/pid/{r}").decode())
                for r in (0, 1, 2)}

        # warm pass: first-traffic compiles + the first handoffs
        warm = [router.submit(p, max_new_tokens=2)
                for p in _prompts(2, rng_seed=7)]
        wres = router.results(wait=True, timeout_s=600)
        assert all(wres[r].status == "ok" for r in warm)
        assert resilience.get_counter("fleet.transfer_completed") >= 1

        # ---- pin BOTH decode pools full via hold_kv hogs claimed into
        # exports, so the next handoffs park on backpressure and the
        # kill below lands mid-transfer deterministically
        hog_stubs = {1: _stub(1), 2: _stub(2)}
        hog_tickets = []
        hog_rid = itertools.count(900)
        for rank, st in hog_stubs.items():
            rids = [next(hog_rid) for _ in range(2)]
            for r, p in zip(rids, _prompts(2, rng_seed=70 + rank)):
                st.submit(p, max_new_tokens=2, rid=r, hold_kv=True)
            for r in rids:
                deadline = time.monotonic() + 120
                t = None
                while t is None and time.monotonic() < deadline:
                    t = st.export_pages(r)
                    time.sleep(0.05)
                assert t is not None, f"hog {r} never held its pages"
                hog_tickets.append((st, t["ticket"]))

        prompts_b = _prompts(4, rng_seed=11)
        rids_b = [router.submit(p, max_new_tokens=8) for p in prompts_b]
        deadline = time.monotonic() + 120
        while not router._transfers and time.monotonic() < deadline:
            router.step()
            time.sleep(0.02)
        assert router._transfers, "no handoff parked mid-transfer"

        # ---- the kill: the prefill source dies holding parked exports
        os.kill(pids[0], signal.SIGKILL)
        for st, tid in hog_tickets:         # free the decode pools
            st.release_export(tid)
        res_b = router.results(wait=True, timeout_s=600)
        assert set(res_b) >= set(rids_b)    # zero requests lost
        want_b = _reference_subprocess_safe(prompts_b, rids_b, 8)
        for rid in rids_b:
            assert res_b[rid].status == "ok", res_b[rid]
            np.testing.assert_array_equal(res_b[rid].tokens, want_b[rid])
        assert router._replicas[0].state == "dead"
        assert resilience.get_counter("fleet.replica_dead") == 1
        assert resilience.get_counter("fleet.transfer_abandoned") >= 1

        # ---- the respawned prefill rank rejoins and hands off again
        deadline = time.monotonic() + 300
        new_pid = None
        while time.monotonic() < deadline:
            try:
                pid = int(fleet_store.get("fleet/pid/0").decode())
            except Exception:
                pid = pids[0]
            if pid != pids[0]:
                new_pid = pid
                break
            time.sleep(0.2)
        assert new_pid is not None, "supervisor did not respawn the rank"
        rpc.get_worker_info("replica0", timeout=300)
        router.add_replica(_stub(0), replica_id=0)
        done0 = resilience.get_counter("fleet.transfer_completed")
        prompts_c = _prompts(2, rng_seed=13)
        rids_c = [router.submit(p, max_new_tokens=4) for p in prompts_c]
        res_c = router.results(wait=True, timeout_s=600)
        want_c = _reference_subprocess_safe(prompts_c, rids_c, 4)
        for rid in rids_c:
            assert res_c[rid].status == "ok", res_c[rid]
            np.testing.assert_array_equal(res_c[rid].tokens, want_c[rid])
        assert resilience.get_counter("fleet.transfer_completed") > done0
    finally:
        router.shutdown()
        supervisor.join(120)
        rpc.shutdown()
        fleet_store.close()
    assert rc_box.get("rc") == 0            # every replica exited clean
