"""Train a small LLaMA on one chip through the whole-step compiled path.

Run: python examples/train_llama_single_chip.py [--cpu]
"""
import sys

if "--cpu" in sys.argv:
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import (
    LlamaConfig,
    LlamaForCausalLM,
    LlamaPretrainingCriterion,
)

paddle.seed(0)
cfg = LlamaConfig(vocab_size=2048, hidden_size=256, intermediate_size=704,
                  num_hidden_layers=4, num_attention_heads=8,
                  max_position_embeddings=256)
model = LlamaForCausalLM(cfg)
crit = LlamaPretrainingCriterion()
opt = paddle.optimizer.AdamW(
    learning_rate=paddle.optimizer.lr.CosineAnnealingDecay(3e-4, T_max=100),
    parameters=model.parameters(), weight_decay=0.1)

# one XLA program: forward + backward + AdamW, buffers donated
step = paddle.jit.TrainStep(model, lambda logits, ids: crit(logits, ids), opt)

rng = np.random.RandomState(0)
for it in range(20):
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (8, 256)))
    loss = step(ids, labels=ids)
    if it % 5 == 0:
        print(f"step {it}: loss {float(loss):.4f}")
print("done")
