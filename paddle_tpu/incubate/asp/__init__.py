"""paddle_tpu.incubate.asp — 2:4 structured sparsity (Automatic SParsity).

Analog of /root/reference/python/paddle/incubate/asp/ (prune_model,
decorate, calculate_density, supported_layers): mask Linear/Conv weights to
n:m patterns and re-apply masks after each optimizer step so training stays
inside the sparse support.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["calculate_density", "create_mask", "prune_model", "decorate",
           "check_sparsity"]

_masks: dict[int, jnp.ndarray] = {}


def calculate_density(x) -> float:
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def create_mask(weight, n=2, m=4):
    """Keep the n largest-magnitude entries of each group of m along the
    last axis (reference create_mask, MaskAlgo_MASK_1D)."""
    arr = np.asarray(weight._value if isinstance(weight, Tensor) else weight)
    flat = arr.reshape(-1, m) if arr.size % m == 0 else None
    if flat is None:
        return np.ones_like(arr)
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat)
    np.put_along_axis(mask, order[:, :n], 1.0, axis=1)
    return mask.reshape(arr.shape)


def check_sparsity(x, n=2, m=4) -> bool:
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if arr.size % m:
        return False
    nz = (arr.reshape(-1, m) != 0).sum(axis=1)
    return bool((nz <= n).all())


def _prunable_params(model):
    from ...nn.layers_common import Linear
    from ...nn.layers_conv import Conv2D

    for _, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, (Linear, Conv2D)):
            if sub.weight is not None:
                yield sub.weight


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to all supported layer weights."""
    for p in _prunable_params(model):
        mask = jnp.asarray(create_mask(p, n, m), p._value.dtype)
        p._value = p._value * mask
        _masks[id(p)] = mask
    return model


def decorate(optimizer):
    """Wrap optimizer.step to re-apply masks after each update (reference
    asp decorate → OptimizerWithSparsityGuarantee)."""

    class _ASPOptimizer:
        def __init__(self, inner):
            self._inner = inner

        def step(self):
            self._inner.step()
            for p in self._inner._parameter_list:
                mask = _masks.get(id(p))
                if mask is not None:
                    p._value = p._value * mask

        def __getattr__(self, name):
            return getattr(self._inner, name)

    return _ASPOptimizer(optimizer)
