#!/usr/bin/env python
"""Repo-root entry for the bench-trend regression harness.

Loads ``paddle_tpu/tools/bench_trend.py`` by FILE PATH (not package
import) so CI can run the series check without importing the framework —
no jax import, no device contact, just JSON parsing over the checked-in
``BENCH_*`` rounds.

    python tools/bench_trend.py [--root DIR] [--json OUT] [--md OUT]

Exit codes: 0 clean, 1 regressions/gate violations, 2 unparseable rounds.
"""
import importlib.util
import os
import sys

_IMPL = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "tools", "bench_trend.py")


def _load():
    spec = importlib.util.spec_from_file_location("_bench_trend", _IMPL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    sys.exit(_load().main())
