"""AMP: auto_cast O1/O2 casting policy, grads cast back to fp32,
GradScaler dynamic scaling, O2 decorate with master weights.

Mirrors reference test/amp/ behaviors.
"""
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_o1_white_op_runs_bf16():
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    w = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        y = paddle.matmul(x, w)
    assert y._value.dtype == jnp.bfloat16
    # outside the context, fp32 again
    y2 = paddle.matmul(x, w)
    assert y2._value.dtype == jnp.float32


def test_o1_black_op_stays_fp32():
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    xb = paddle.cast(x, "bfloat16")
    with paddle.amp.auto_cast(level="O1"):
        s = paddle.nn.functional.softmax(xb)
    assert s._value.dtype == jnp.float32


def test_o1_gray_op_keeps_dtype():
    x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    with paddle.amp.auto_cast(level="O1"):
        y = x + x
    assert y._value.dtype == jnp.float32


def test_grads_cast_back_to_param_dtype():
    layer = nn.Linear(8, 4)
    x = paddle.to_tensor(np.random.rand(2, 8).astype(np.float32))
    with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
        y = layer(x)
        loss = y.astype("float32").sum()
    loss.backward()
    g = layer.weight.grad
    assert g is not None
    assert g._value.dtype == jnp.float32  # cast-back through the tape


def test_custom_lists():
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    w = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    with paddle.amp.auto_cast(level="O1", custom_black_list=["matmul"]):
        y = paddle.matmul(x, w)
    assert y._value.dtype == jnp.float32


def test_o2_decorate_master_weights():
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    model, opt = paddle.amp.decorate(model, opt, level="O2", dtype="bfloat16")
    assert model.weight._value.dtype == jnp.bfloat16
    x = paddle.to_tensor(np.random.rand(2, 8).astype(np.float32))
    with paddle.amp.auto_cast(level="O2", dtype="bfloat16"):
        loss = model(x).astype("float32").sum()
    loss.backward()
    opt.step()
    # master weights materialized in fp32
    assert opt._master_weights
    for mv in opt._master_weights.values():
        assert mv.dtype == jnp.float32


def test_grad_scaler_dynamic():
    p = paddle.Parameter(jnp.ones(4, jnp.float32))
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0,
                                   incr_every_n_steps=2,
                                   decr_every_n_nan_or_inf=1)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])

    loss = (p * 2).sum()
    scaler.scale(loss).backward()
    assert float(p.grad._value[0]) == 16.0  # scaled grad
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(np.asarray(p.grad._value), 2.0 * np.ones(4))
    # param updated with unscaled grad
    np.testing.assert_allclose(np.asarray(p._value), 1.0 - 0.1 * 2.0)

    # non-finite grad: skip step, decrease scale
    opt.clear_grad()
    before = np.asarray(p._value).copy()
    bad = (p * float("inf")).sum()
    scaler.scale(bad).backward()
    scaler.step(opt)
    scaler.update()
    np.testing.assert_allclose(np.asarray(p._value), before)
    assert scaler.get_loss_scaling() == 4.0


def test_bf16_training_matches_fp32_trajectory():
    """O1 bf16 loss curve tracks fp32 within tolerance (VERDICT item 7)."""
    def run(amp_on):
        paddle.seed(7)
        model = nn.Linear(16, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=model.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).rand(8, 16).astype(np.float32))
        t = paddle.to_tensor(np.random.RandomState(1).rand(8, 1).astype(np.float32))
        losses = []
        for _ in range(10):
            if amp_on:
                with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
                    y = model(x)
                loss = ((y.astype("float32") - t) ** 2).mean()
            else:
                loss = ((model(x) - t) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        return losses

    l32 = run(False)
    lbf = run(True)
    assert lbf[-1] < lbf[0]
    np.testing.assert_allclose(lbf[-1], l32[-1], rtol=0.2)


def test_operator_stats_collection(capsys):
    from paddle_tpu.amp import debugging

    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    w = paddle.to_tensor(np.random.rand(8, 4).astype(np.float32))
    with debugging.collect_operator_stats():
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            y = paddle.matmul(x, w)
            z = paddle.nn.functional.softmax(y)
    out = capsys.readouterr().out
    assert "matmul" in out and "bfloat16" in out
    assert "softmax" in out and "float32" in out


def test_check_numerics():
    from paddle_tpu.amp.debugging import check_numerics

    ok = paddle.to_tensor(np.ones(3, np.float32))
    check_numerics(ok, "identity", "x")
    import pytest as _pytest

    bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
    with _pytest.raises(FloatingPointError, match="NaN"):
        check_numerics(bad, "op", "y")


def test_unscale_then_step_divides_once():
    """Review regression: unscale_ -> clip -> step() must not unscale twice."""
    p = paddle.Parameter(jnp.ones(4, jnp.float32))
    scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[p])
    (p * 2).sum().backward()  # true grad = 2; scaled backward would be 16
    scaler.scale(paddle.to_tensor(np.float32(0.0)))  # (scale used on loss)
    # emulate scaled grads as scale(loss).backward() would produce
    p._grad._value = p._grad._value * 8.0
    scaler.unscale_(opt)
    np.testing.assert_allclose(np.asarray(p.grad._value), 2.0 * np.ones(4))
    scaler.step(opt)  # must NOT divide again
    np.testing.assert_allclose(np.asarray(p._value), 1.0 - 0.1 * 2.0)
    # next step: unscale works again
    opt.clear_grad()
    (p * 2).sum().backward()
    p._grad._value = p._grad._value * 8.0
    scaler.unscale_(opt)
    np.testing.assert_allclose(np.asarray(p.grad._value), 2.0 * np.ones(4))
