"""paddle_tpu.utils — misc utilities.

Analog of /root/reference/python/paddle/utils/ (cpp_extension, deprecated,
lazy_import, unique_name).
"""
from __future__ import annotations

import functools
import warnings

from . import cpp_extension  # noqa: F401

__all__ = ["cpp_extension", "deprecated", "try_import", "unique_name", "flatten"]


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference utils/deprecated.py)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__name__} is deprecated since {since or 'now'}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        raise ImportError(
            err_msg or f"optional dependency {module_name!r} is missing "
            "(this environment installs nothing at runtime)") from e


class _UniqueName:
    """reference utils/unique_name.py: process-wide name generator."""

    def __init__(self):
        self._counters = {}

    def generate(self, key="tmp"):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{key}_{n}"

    def guard(self, new_generator=None):
        import contextlib

        @contextlib.contextmanager
        def _guard():
            saved = dict(self._counters)
            try:
                yield
            finally:
                self._counters = saved

        return _guard()


unique_name = _UniqueName()


def flatten(nested):
    """Flatten nested lists/tuples/dicts to a leaf list (utils/layers_utils)."""
    out = []

    def walk(x):
        if isinstance(x, dict):
            for k in sorted(x):
                walk(x[k])
        elif isinstance(x, (list, tuple)):
            for v in x:
                walk(v)
        else:
            out.append(x)

    walk(nested)
    return out


def require_version(min_version, max_version=None):
    """Reference utils.require_version over the installed framework
    version (version.py full_version)."""
    from .. import version as _v

    def parse(s):
        return tuple(int(p) for p in str(s).split(".")[:3] if p.isdigit())

    cur = parse(getattr(_v, "full_version", "0.0.0"))
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {getattr(_v, 'full_version', '?')} < "
            f"required minimum {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {getattr(_v, 'full_version', '?')} > "
            f"required maximum {max_version}")


def run_check():
    """Reference paddle.utils.run_check: verify the install can compute on
    its accelerator — one small jitted matmul on the default backend."""
    import jax
    import jax.numpy as jnp

    out = jax.jit(lambda a, b: a @ b)(jnp.ones((4, 4)), jnp.ones((4, 4)))
    assert float(out.sum()) == 64.0
    n = jax.device_count()
    print(f"PaddlePaddle (paddle_tpu) works on {n} "
          f"{jax.default_backend()} device{'s' if n != 1 else ''}.")


__all__ += ["require_version", "run_check"]
