"""KV-cache decode correctness + generate() API."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (
    LlamaForCausalLM,
    generate,
    llama_tiny_config,
)


def test_cached_decode_matches_full_forward():
    """Greedy decode with KV cache must pick the same tokens as rerunning the
    full sequence each step (RoPE offsets included)."""
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = np.random.randint(0, 256, (2, 8))

    # full-recompute greedy loop (oracle)
    cur = ids.copy()
    for _ in range(5):
        logits = model(paddle.to_tensor(cur))
        nxt = np.asarray(logits._value)[:, -1, :].argmax(-1)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)

    out = generate(model, paddle.to_tensor(ids), max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out._value), cur)


def test_generate_sampling_and_eos():
    paddle.seed(1)
    model = LlamaForCausalLM(llama_tiny_config()).eval()
    ids = paddle.to_tensor(np.random.randint(0, 256, (1, 4)))
    out = generate(model, ids, max_new_tokens=6, do_sample=True,
                   temperature=0.8, top_k=10)
    assert out.shape[1] == 10
    out2 = generate(model, ids, max_new_tokens=6, do_sample=True, top_p=0.9)
    assert out2.shape[1] == 10
