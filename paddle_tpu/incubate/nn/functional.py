"""incubate.nn.functional — fused-op functional surface.

Analog of /root/reference/python/paddle/incubate/nn/functional/ — thin
names over the already-fused implementations (Pallas flash attention +
XLA-fused compositions).
"""
from ...ops import fused_linear_cross_entropy  # noqa: F401
from ...ops import rms_norm as fused_rms_norm  # noqa: F401
from ...ops import (  # noqa: F401
    rotary_position_embedding as fused_rotary_position_embedding,
)
from ...ops import (  # noqa: F401
    scaled_dot_product_attention as fused_dot_product_attention,
)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    from ...nn import functional as F
    from ...ops import matmul

    if transpose_weight:
        y = matmul(x, weight, transpose_y=True)
        return y + bias if bias is not None else y
    return F.linear(x, weight, bias)


def fused_feedforward(x, linear1_weight, linear2_weight, *args, **kwargs):
    """XLA fuses the bias/act/dropout chain; provided for API parity."""
    from ...nn import functional as F
    from ...ops import matmul

    h = F.gelu(matmul(x, linear1_weight))
    return matmul(h, linear2_weight)


def fused_layer_norm(x, weight, bias, epsilon=1e-5, begin_norm_axis=1):
    from ...ops import layer_norm

    return layer_norm(x, weight, bias, epsilon=epsilon,
                      begin_norm_axis=begin_norm_axis)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True,
        mode="upscale_in_train", name=None):
    """``layer_norm(residual + dropout(x + bias))`` — one fused Pallas pass
    (analog of paddle/phi/kernels/fusion/gpu/
    fused_bias_dropout_residual_layer_norm); registry op, so it composes
    with eager autograd and the jit caches."""
    from ...core import random as _random
    from ...ops import fused_bias_dropout_residual_layer_norm as _op

    import jax

    rng_key = (jax.random.key_data(_random.next_key())
               if (training and dropout_rate > 0.0) else None)
    return _op(x, residual, bias, ln_scale, ln_bias,
               dropout_rate=dropout_rate, ln_epsilon=ln_epsilon,
               training=training, mode=mode, rng_key=rng_key)
