"""Top-level API parity: every name in the reference's paddle.__all__
exists on paddle_tpu (the audit that drove the round-2 compat tranche),
plus behavior checks for the in-place variants and compat helpers."""
import ast
import os

import numpy as np
import pytest

import paddle_tpu as paddle

_REFERENCE = "/root/reference/python/paddle/__init__.py"


def _reference_all():
    src = open(_REFERENCE).read()
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    return [ast.literal_eval(e) for e in node.value.elts]
    raise AssertionError("reference __all__ not found")


@pytest.mark.skipif(not os.path.exists(_REFERENCE),
                    reason="reference checkout not present in this "
                           "container (audit runs where it is)")
def test_every_reference_name_exists():
    missing = [n for n in _reference_all() if not hasattr(paddle, n)]
    assert missing == [], f"missing top-level names: {missing}"


def test_inplace_variants_rebind_value():
    x = paddle.to_tensor(np.float32([1.0, 4.0]))
    out = x.sqrt_()
    assert out is x
    np.testing.assert_allclose(np.asarray(x._value), [1.0, 2.0])
    x.tanh_()
    x.clip_(0.0, 0.5)
    assert float(x.max()) <= 0.5
    y = paddle.to_tensor(np.float32([-2.0]))
    paddle.abs_(y)  # functional form
    np.testing.assert_allclose(np.asarray(y._value), [2.0])


def test_random_inplace_fill():
    paddle.seed(7)
    z = paddle.zeros([2000])
    z.normal_(3.0, 0.5)
    assert abs(float(z.mean()) - 3.0) < 0.1
    z.uniform_(0.0, 1.0)
    assert 0.0 <= float(z.min()) and float(z.max()) <= 1.0
    z.bernoulli_(0.25)
    assert abs(float(z.mean()) - 0.25) < 0.05
    draws1 = np.asarray(z._value).copy()
    z.bernoulli_(0.25)
    assert not np.array_equal(draws1, np.asarray(z._value))


def test_compat_helpers():
    assert paddle.iinfo("int16").max == 32767
    assert paddle.finfo(paddle.float32).bits == 32
    assert paddle.in_dynamic_mode()
    paddle.enable_static()
    try:
        assert not paddle.in_dynamic_mode()
    finally:
        paddle.disable_static()
    with pytest.raises(RuntimeError, match="TPU-native"):
        paddle.CUDAPlace(0)
    assert paddle.get_cuda_rng_state() == []
    batches = [len(b) for b in paddle.batch(lambda: iter(range(7)), 3)()]
    assert batches == [3, 3, 1]
    assert [len(b) for b in paddle.batch(
        lambda: iter(range(7)), 3, drop_last=True)()] == [3, 3]
    # view: reshape form and bitcast form
    v = paddle.view(paddle.ones([2, 2]), [4])
    assert v.shape == [4]
    assert paddle.view(paddle.ones([2, 2]), "int32").dtype == paddle.int32
    # mod/floor_mod aliases
    np.testing.assert_allclose(
        float(paddle.mod(paddle.to_tensor(np.float32([7.0])),
                         paddle.to_tensor(np.float32([4.0])))), 3.0)
    # reverse == flip
    np.testing.assert_allclose(
        np.asarray(paddle.reverse(
            paddle.to_tensor(np.float32([1, 2, 3])), axis=0)._value),
        [3, 2, 1])


def test_new_ops():
    x = paddle.to_tensor(np.array([[0.0, 0.0], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(np.asarray(paddle.pdist(x)._value), [5.0])
    big = paddle.ones([4, 3, 2])
    t = paddle.ones([3, 1])
    out = paddle.reduce_as(big, t)
    assert out.shape == [3, 1]
    np.testing.assert_allclose(np.asarray(out._value), 8.0)
    shifted = paddle.bitwise_left_shift(
        paddle.to_tensor(np.array([1, 2], np.int32)),
        paddle.to_tensor(np.array([3, 1], np.int32)))
    np.testing.assert_array_equal(np.asarray(shifted._value), [8, 4])
    edges = paddle.histogram_bin_edges(
        paddle.to_tensor(np.arange(10.0, dtype=np.float32)), bins=5)
    assert edges.shape == [6]
