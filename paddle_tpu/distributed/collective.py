"""Collective communication API — the host control plane + in-program ops.

The reference splits communication into (a) eager ``ProcessGroup`` objects
driving NCCL from Python
(/root/reference/paddle/phi/core/distributed/collective/process_group.h:48,
python/paddle/distributed/communication/) and (b) collective *ops* compiled
into graphs. The TPU-native equivalent preserves that split
(SURVEY.md §5 "Distributed communication backend"):

* **In-program collectives** (the hot path) are XLA HLO — expressed here as
  thin wrappers over ``lax.psum``/``all_gather``/… keyed by mesh *axis
  name*, usable inside ``shard_map``/``pjit``. XLA schedules them onto
  ICI/DCN; there is no runtime ProcessGroup.
* **Host control plane**: ``init_parallel_env`` maps to
  ``jax.distributed.initialize`` (multi-controller over DCN),
  ``get_rank``/``get_world_size`` to process index/count. Eager collective
  calls on dist tensors execute a tiny jit'd program over the tensor's mesh.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core import resilience
from ..core.resilience import CommTimeoutError, Deadline, RetryPolicy, inject
from ..core.tensor import Tensor
from .placement import Partial, Replicate, Shard
from .process_mesh import ProcessMesh, get_mesh

__all__ = [
    "Group", "new_group", "get_rank", "get_world_size", "get_group",
    "init_parallel_env", "is_initialized", "barrier",
    "all_reduce", "all_gather", "all_gather_object", "broadcast",
    "reduce", "scatter", "all_to_all", "reduce_scatter", "send", "recv",
    "isend", "irecv",
    "ReduceOp", "P2POp", "batch_isend_irecv", "destroy_process_group",
    "in_dynamic_mode_collectives", "CommTimeoutError",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communicator: a set of ranks, optionally bound to one axis of a
    ProcessMesh (reference python/paddle/distributed/communication/group.py:29).
    When bound to a mesh axis, collectives over the group lower to XLA
    collectives over that axis."""

    _next_gid = 0

    def __init__(self, ranks, mesh: ProcessMesh | None = None, axis=None,
                 gid=None):
        self.ranks = list(ranks)
        self.mesh = mesh
        self.axis = axis
        if gid is None:
            gid = Group._next_gid
            Group._next_gid += 1
        self.id = gid

    @property
    def nranks(self):
        if self.mesh is not None and self.axis is not None:
            return self.mesh.get_dim_size(self.axis)
        return len(self.ranks)

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axis={self.axis})"


_default_group: Group | None = None
_groups: dict[int, Group] = {}


def init_parallel_env():
    """Bootstrap multi-controller execution (reference
    python/paddle/distributed/parallel.py:978 init_parallel_env → TCPStore +
    ProcessGroupNCCL). TPU-native: ``jax.distributed.initialize`` — PJRT's
    distributed KV store is the TCPStore analog; intra-program collectives
    need no process groups. Single-process runs are a no-op."""
    global _default_group
    if _default_group is not None:
        return _default_group
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if coord and nprocs > 1:
        # NOTE: must run before anything touches the XLA backend (even
        # jax.process_count() would initialize it) — core/random keys are
        # lazy for exactly this reason.
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        try:
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=nprocs,
                process_id=rank,
            )
        except RuntimeError as e:
            if "already" not in str(e):  # double-init is fine; else re-raise
                raise
    _default_group = Group(ranks=list(range(len(jax.devices()))), gid=0)
    _groups[0] = _default_group
    return _default_group


def is_initialized():
    return _default_group is not None


def destroy_process_group(group=None):
    global _default_group
    if group is None or group is _default_group:
        _default_group = None
        _groups.clear()


def get_rank(group: Group | None = None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group: Group | None = None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def get_group(gid=0):
    return _groups.get(gid)


def new_group(ranks=None, backend=None, timeout=None):
    g = Group(ranks=list(ranks) if ranks is not None else
              list(range(len(jax.devices()))))
    _groups[g.id] = g
    return g


def barrier(group=None):
    """Block until every rank reaches this point. With a parallel env
    initialized AND a gang store available (multi-process launch), a
    WHOLE-WORLD barrier (``group`` None or the default group) is a real
    store-backed :func:`gang.gang_barrier` over the gang — it fails
    fast with ``PeerFailureError`` when a peer dies instead of hanging.
    Subgroup barriers (and the single-controller case) degrade to the
    device round-trip, which only orders THIS process's async work —
    routing a subgroup through the gang barrier would deadlock the
    non-member ranks' arrival count."""
    from . import gang

    # always flush this process's pending async device work first — the
    # gang rendezvous must be a strict superset of the old semantics
    (jnp.zeros(()) + 1).block_until_ready()
    if _default_group is not None and (group is None
                                       or group is _default_group):
        ctx = gang.gang_context()
        if ctx is not None and ctx.world_size > 1:
            seq = ctx.next_seq("collective.barrier")
            gang.gang_barrier(f"collective.barrier/{seq}", ctx=ctx)


# ------------------------------------------------------------------
# Eager collectives.
#
# Semantics: under single-controller jax every array is already a global
# value — a host-level all_reduce over a *replicated* tensor is the identity
# (matching single-process reference behavior). Over a tensor with a Partial
# or Shard placement hint, the collective executes a tiny compiled program
# over the tensor's mesh. Inside shard_map'd code, use the functional ops
# below with an axis name.
# ------------------------------------------------------------------

def _value(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap_like(x, v):
    if isinstance(x, Tensor):
        x._value = v
        return x
    return v


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reference communication/all_reduce.py. Identity for replicated values
    (single-controller); reduces over shard axes for row-sharded hints."""
    hint = getattr(tensor, "_placements_hint", None)
    if hint is None:
        return tensor
    mesh, placements = hint
    v = _value(tensor)
    axes = [mesh.dim_names[i] for i, pl in enumerate(placements)
            if isinstance(pl, Partial)]
    if not axes:
        return tensor
    # Partial→Replicate reshard is where a real all-reduce happens; in eager
    # single-controller mode Partial never materializes, so this is metadata.
    new_pl = [Replicate() if isinstance(pl, Partial) else pl
              for pl in placements]
    tensor._placements_hint = (mesh, new_pl)
    return _wrap_like(tensor, v)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """Gather shards to the full value on every rank. For a dist tensor this
    is a replicate-reshard; the gathered per-rank blocks are appended to
    ``tensor_list`` (reference all_gather semantics)."""
    from .api import reshard

    hint = getattr(tensor, "_placements_hint", None)
    if hint is None:
        n = get_world_size(group) if group else 1
        tensor_list.extend([tensor] * max(n, 1))
        return tensor_list
    mesh, placements = hint
    full = reshard(tensor, mesh, [Replicate()] * mesh.ndim)
    # split back into the per-rank blocks along the sharded dim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            n = mesh.shape[mesh_dim]
            parts = jnp.split(full._value, n, axis=pl.get_dim())
            tensor_list.extend(Tensor._from_value(p) for p in parts)
            return tensor_list
    tensor_list.append(full)
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    n = max(get_world_size(group) if group else 1, 1)
    object_list.extend([obj] * n)
    return object_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    return tensor  # single-controller arrays are already globally consistent


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        r = get_rank(group)
        src_t = tensor_list[r if 0 <= r < len(tensor_list) else 0]
        return _wrap_like(tensor, _value(src_t))
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    out_tensor_list.extend(in_tensor_list)
    return out_tensor_list


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    r = max(get_rank(group), 0)
    vals = [_value(t) for t in tensor_list]
    total = vals[0]
    for v in vals[1:]:
        total = total + v
    n = max(get_world_size(group) if group else 1, 1)
    parts = jnp.split(total, n, axis=0) if n > 1 else [total]
    return _wrap_like(tensor, parts[min(r, len(parts) - 1)])


# Eager host-level p2p (reference ProcessGroup send/recv,
# python/paddle/distributed/communication/send.py / recv.py): in the
# multi-controller world each (src, dst) pair keeps an implicit message
# sequence; the payload moves over the jax coordination-service KV — the
# host/DCN control-plane path (inside compiled programs use
# paddle_tpu.distributed.comm_ops.ppermute, which rides ICI).
_p2p_seq: dict = {}


def _p2p_client():
    from jax._src import distributed

    client = distributed.global_state.client
    if client is None:
        raise RuntimeError(
            "dist.send/recv need a multi-controller run "
            "(dist.init_parallel_env under launch/spawn); inside compiled "
            "programs use paddle_tpu.distributed.comm_ops.ppermute")
    return client


def _p2p_key(src, dst):
    seq = _p2p_seq.get((src, dst), 0)
    _p2p_seq[(src, dst)] = seq + 1
    return f"p2p/{src}->{dst}/{seq}"


# transient-for-the-transport errors: connection/timeouts/OS plus
# RuntimeError, because the jax coordination client surfaces
# DEADLINE_EXCEEDED/UNAVAILABLE as JaxRuntimeError. TypeError/ValueError
# (programming errors) propagate immediately, un-retried and un-wrapped.
_TRANSIENT = (ConnectionError, TimeoutError, OSError, RuntimeError)


def _kv_publish(key, payload: bytes, deadline: Deadline | None = None):
    """Publish raw bytes on the coordination-service KV (shared by eager
    p2p and the object collectives). Transient coordinator errors are
    retried with backoff under ``deadline``."""
    import base64

    client = _p2p_client()  # usage errors (no multi-controller) don't retry
    enc = base64.b64encode(payload).decode()

    def _set():
        inject("kv_publish")
        client.key_value_set(key, enc)

    RetryPolicy(retry_on=_TRANSIENT).call(
        _set, deadline=deadline, describe=f"kv publish {key!r}")


def _kv_fetch(key, timeout_ms=None, consume=True, src=None,
              dst=None) -> bytes:
    """Blocking fetch under a wall-clock deadline (``timeout_ms``, default
    FLAGS_comm_timeout_ms). Transient coordinator errors — including
    injected ``kv_drop`` faults — are retried with backoff; when the
    deadline or attempt budget runs out a ``CommTimeoutError`` naming
    key/src/dst is raised instead of hanging. ``consume`` deletes the key
    afterwards so per-call channels never grow the coordinator's store;
    delete failures are counted (``kv_delete_failures``), not swallowed
    silently, so leaked keys stay observable."""
    import base64

    from . import gang

    client = _p2p_client()
    if timeout_ms is None:
        timeout_ms = resilience.flag("FLAGS_comm_timeout_ms")
    deadline = Deadline.from_ms(timeout_ms)

    def _get():
        inject("kv_drop")
        det = gang.get_active_detector()
        if det is None:
            slice_ms = max(int(min(deadline.remaining_ms(), timeout_ms)), 1)
            return client.blocking_key_value_get(key, slice_ms)
        # gang-aware wait: block at most one heartbeat lease per slice,
        # re-checking the detector in between — a dead sender surfaces as
        # PeerFailureError within ~one lease instead of this rank burning
        # the full KV timeout on a payload that can never arrive.
        # PeerFailureError is deliberately not a _TRANSIENT subclass, so
        # it escapes the retry policy unwrapped.
        phase = f"kv_fetch {key}"
        while True:
            det.check(phase)
            slice_ms = max(int(min(deadline.remaining_ms(), timeout_ms,
                                   det.lease * 1000.0)), 1)
            try:
                return client.blocking_key_value_get(key, slice_ms)
            except _TRANSIENT as e:
                if (deadline.remaining_ms() <= 0
                        or "DEADLINE_EXCEEDED" not in str(e)):
                    raise
                # the lease slice elapsed with no payload — not a failure;
                # loop to re-check the gang and keep waiting

    try:
        raw = RetryPolicy(retry_on=_TRANSIENT).call(
            _get, deadline=deadline, describe=f"kv fetch {key!r}")
    except _TRANSIENT as e:
        raise CommTimeoutError(
            f"p2p fetch of key {key!r} (src={src}, dst={dst}) failed after "
            f"retries within {timeout_ms}ms: {e}",
            key=key, src=src, dst=dst) from e
    if consume:
        try:
            client.key_value_delete(key)
        except Exception as e:
            resilience.bump_counter("kv_delete_failures")
            resilience.logger.warning(
                "key_value_delete(%r) failed (leaked coordinator key): %s",
                key, e)
    return base64.b64decode(raw)


class _DoneTask:
    """Already-completed p2p task (publishing never blocks)."""

    def __init__(self, tensor):
        self._tensor = tensor

    def wait(self):
        return self._tensor


def send(tensor, dst=0, group=None, sync_op=True):
    """Send ``tensor`` to process ``dst`` (pairwise-ordered with the
    peer's ``recv``). Publishing is non-blocking; the key is consumed by
    the receiver."""
    key = _p2p_key(jax.process_index(), int(dst))
    val = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    _kv_publish(key, np.asarray(val).tobytes())
    return None if sync_op else _DoneTask(tensor)


class _RecvTask:
    def __init__(self, tensor, key, timeout_ms, src=None, dst=None):
        self._tensor, self._key, self._timeout = tensor, key, timeout_ms
        self._src, self._dst = src, dst
        self._done = False

    def wait(self):
        if self._done:
            return self._tensor
        raw = _kv_fetch(self._key, self._timeout,  # consumed on read
                        src=self._src, dst=self._dst)
        t = self._tensor
        is_tensor = isinstance(t, Tensor)  # raw jax arrays also expose a
        val = t._value if is_tensor else t  # _value property — be explicit
        arr = np.frombuffer(raw,
                            dtype=np.dtype(val.dtype)).reshape(val.shape)
        new = jnp.asarray(arr)
        if is_tensor:
            t._value = new  # reference recv fills the passed tensor
        else:
            self._tensor = new
        self._done = True
        return self._tensor


def recv(tensor, src=0, group=None, sync_op=True, timeout_ms=None):
    """Receive into ``tensor`` (shape/dtype contract, reference
    semantics) from process ``src``; blocks when ``sync_op``. The fetch
    runs under a deadline (``timeout_ms``, default FLAGS_comm_timeout_ms)
    and raises ``CommTimeoutError`` naming key/src/dst on expiry."""
    dst = jax.process_index()
    task = _RecvTask(tensor, _p2p_key(int(src), dst),
                     timeout_ms, src=int(src), dst=dst)
    if sync_op:
        # wait() returns the FILLED value — for raw-array buffers (no
        # in-place _value) the original object cannot carry the payload
        return task.wait()
    return task


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group, sync_op=False)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op, self.tensor, self.peer, self.group = op, tensor, peer, group


def batch_isend_irecv(p2p_op_list):
    """Post every send first (publishing never blocks), then return recv
    tasks — the symmetric neighbor-exchange pattern completes without
    deadlock regardless of call order (reference batch_isend_irecv over
    ProcessGroup::Send/Recv)."""
    for op in p2p_op_list:
        if op.op not in (send, isend, recv, irecv):
            raise ValueError(
                f"P2POp.op must be dist.send/isend/recv/irecv, got {op.op!r}")
    # one task PER OP in list order (reference contract): sends post first
    # (publishing never blocks) so the symmetric neighbor exchange
    # completes regardless of call order, recvs return blocking tasks
    tasks: list = [None] * len(p2p_op_list)
    for i, op in enumerate(p2p_op_list):
        if op.op in (send, isend):
            tasks[i] = send(op.tensor, op.peer, op.group,
                            sync_op=False)
    for i, op in enumerate(p2p_op_list):
        if op.op in (recv, irecv):
            tasks[i] = recv(op.tensor, op.peer, op.group, sync_op=False)
    return tasks


def in_dynamic_mode_collectives():
    return True
