"""Fused transformer layers.

Analog of /root/reference/python/paddle/incubate/nn/layer/fused_transformer.py
(FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer,
FusedMultiTransformer) over the fusion kernel set
(paddle/phi/kernels/fusion/gpu/fused_attention_kernel.cu:40,
fused_feedforward_kernel.cu, fused_multi_transformer_op.cu).

TPU-native fusion story: the attention core routes to the Pallas flash
kernel (ops/pallas/flash_attention.py); everything else — bias add,
residual, dropout, layer-norm — is left to XLA's fuser, which emits the
same fused elementwise+reduce kernels the CUDA side hand-writes. The layer
classes exist for API parity (BERT BASELINE config 2 builds from them) and
to keep pre/post-LN + residual wiring identical to the reference.
"""
from __future__ import annotations

from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer_base import Layer
from ...nn.layers_common import Dropout, Linear
from ...nn.layers_norm import LayerNorm
from ...ops import concat, reshape, scaled_dot_product_attention

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedMultiTransformer",
           "FusedBiasDropoutResidualLayerNorm"]


class FusedMultiHeadAttention(Layer):
    """fused_attention_kernel.cu:40 semantics: (optional pre-LN) → qkv proj
    → attention → out proj → dropout → residual (+ optional post-LN)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, linear_weight_attr=None,
                 pre_ln_epsilon=1e-5, ln_epsilon=1e-5, nranks=1, ring_id=-1,
                 name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim,
                               weight_attr=qkv_weight_attr)
        self.out_proj = Linear(embed_dim, embed_dim,
                               weight_attr=linear_weight_attr)
        self.pre_ln = LayerNorm(embed_dim, epsilon=pre_ln_epsilon)
        self.ln = LayerNorm(embed_dim, epsilon=ln_epsilon)
        self.dropout = Dropout(dropout_rate)
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        b, s, _ = x.shape
        qkv = reshape(self.qkv_proj(x),
                      [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = self.out_proj(reshape(out, [b, s, self.embed_dim]))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.ln(out)
        return out


class FusedFeedForward(Layer):
    """fused_feedforward_kernel.cu: (optional pre-LN) → linear → act →
    dropout → linear → dropout → residual (+ optional post-LN)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear2_weight_attr=None, name=None):
        super().__init__()
        self.linear1 = Linear(d_model, dim_feedforward,
                              weight_attr=linear1_weight_attr)
        self.linear2 = Linear(dim_feedforward, d_model,
                              weight_attr=linear2_weight_attr)
        self.ln = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)
        self.activation = getattr(F, activation)
        self.normalize_before = normalize_before

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.ln(x)
        x = self.act_dropout(self.activation(self.linear1(x)))
        x = residual + self.dropout(self.linear2(x))
        if not self.normalize_before:
            x = self.ln(x)
        return x


class FusedTransformerEncoderLayer(Layer):
    """fused_transformer.py FusedTransformerEncoderLayer = fused MHA +
    fused FFN (BERT BASELINE config 2 building block)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(Layer):
    """Decoder-inference stack (fused_multi_transformer_op.cu): N pre-LN
    blocks with shared config; the per-step KV cache path is served by the
    models' cache plumbing rather than one monolithic kernel."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1,
                 nranks=1, ring_id=-1):
        super().__init__()
        from ...nn.layers_common import LayerList

        self.layers = LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward,
                dropout_rate=dropout_rate, activation=activation,
                normalize_before=normalize_before)
            for _ in range(num_layers)
        ])

    def forward(self, src, attn_mask=None, caches=None):
        x = src
        for layer in self.layers:
            x = layer(x, src_mask=attn_mask)
        return x


class FusedBiasDropoutResidualLayerNorm(Layer):
    """``layer_norm(residual + dropout(x + linear_bias))`` as a layer with
    learnable LN scale/bias (+ optional linear bias) — the analog of the
    reference's FusedBiasDropoutResidualLayerNorm
    (python/paddle/incubate/nn/layer/fused_transformer.py:94), backed by
    the fused Pallas kernel via the registry op."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        if embed_dim <= 0:
            raise ValueError(
                f"embed_dim must be positive, got {embed_dim}")
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True)

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm

        return fused_bias_dropout_residual_layer_norm(
            x, residual, self.linear_bias, self.ln_scale, self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self._epsilon,
            training=self.training)

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}, dropout_rate={self.dropout_rate}"
