"""Error machinery — the PADDLE_ENFORCE analog.

Reference: paddle/phi/core/enforce.h + paddle/common/ (error codes,
argument-checking macros with rich context, stack summaries). Python-native
design: ``enforce_*`` helpers raise typed errors with the same category
names as the reference's error codes, and ``op_error_context`` wraps an op
dispatch so a failing kernel reports the op name and every operand's
shape/dtype — what the generated C++ ad_funcs print via
PADDLE_ENFORCE's demangled context.
"""
from __future__ import annotations

import contextlib

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "PreconditionNotMetError",
    "UnimplementedError", "enforce", "enforce_eq", "enforce_gt",
    "enforce_shape_match", "op_error", "op_error_context",
]


class EnforceNotMet(RuntimeError):
    """Base of the enforce error family (reference enforce.h EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


def enforce(cond, message, error_cls=InvalidArgumentError):
    if not cond:
        raise error_cls(message)


def enforce_eq(a, b, message=None, error_cls=InvalidArgumentError):
    if a != b:
        raise error_cls(message or f"expected {a!r} == {b!r}")


def enforce_gt(a, b, message=None, error_cls=InvalidArgumentError):
    if not a > b:
        raise error_cls(message or f"expected {a!r} > {b!r}")


def enforce_shape_match(shape_a, shape_b, message=None):
    """Broadcast-compatible check (the most common kernel precondition)."""
    ra, rb = list(shape_a)[::-1], list(shape_b)[::-1]
    for da, db in zip(ra, rb):
        if da != db and da != 1 and db != 1:
            raise InvalidArgumentError(
                message or f"shapes {tuple(shape_a)} and {tuple(shape_b)} "
                "are not broadcast-compatible")


def _describe(v):
    if isinstance(v, list):
        return "[" + ", ".join(_describe(x) for x in v) + "]"
    if v is None:
        return "None"
    shape = getattr(v, "shape", None)
    dtype = getattr(v, "dtype", None)
    if shape is not None:
        return f"Tensor(shape={tuple(shape)}, dtype={dtype})"
    return repr(v)


def op_error(op_name, input_names, in_vals, attrs, exc):
    """Build the rich kernel-failure error — the dispatcher-level analog of
    PADDLE_ENFORCE's context block (built only on the failure path, so the
    dispatch hot loop pays nothing)."""
    args = ", ".join(
        f"{n}={_describe(v)}" for n, v in zip(input_names, in_vals))
    ats = ", ".join(f"{k}={v!r}" for k, v in attrs.items())
    return InvalidArgumentError(
        f"(InvalidArgument) operator `{op_name}` failed: {exc}\n"
        f"  [operands] {args}\n"
        f"  [attributes] {ats}")


@contextlib.contextmanager
def op_error_context(op_name, input_names, in_vals, attrs):
    """Context-manager form of ``op_error`` for non-hot callers."""
    try:
        yield
    except EnforceNotMet:
        raise
    except (TypeError, ValueError, IndexError, ZeroDivisionError) as e:
        raise op_error(op_name, input_names, in_vals, attrs, e) from e
