from . import autograd, dtype, flags, health, place, random, resilience
from .autograd import enable_grad, grad, is_grad_enabled, no_grad
from .dtype import (
    bfloat16,
    bool_,
    complex64,
    complex128,
    convert_dtype,
    float8_e4m3fn,
    float8_e5m2,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    to_jax_dtype,
    uint8,
)
from .flags import define_flag, get_flags, set_flags
from .place import (
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_tpu,
    set_device,
)
from .random import get_rng_state, seed, set_rng_state
from .tensor import Parameter, Tensor, to_tensor
