"""Pallas decode-serving kernels: paged / masked decode attention
(analogs of block_multi_head_attention_kernel.cu and
masked_multihead_attention_kernel.cu) — numerics vs the jnp composition,
plus end-to-end generation equivalence across cache types.
"""
import math

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.decode_attention import (
    masked_decode_attention,
    paged_attention,
)


def _ref_decode(q, k, v, lens):
    b_, h_, d_ = q.shape
    g = h_ // k.shape[2]
    o = np.zeros((b_, h_, d_), np.float32)
    for b in range(b_):
        kk = np.asarray(k)[b, :int(lens[b])]
        vv = np.asarray(v)[b, :int(lens[b])]
        for h in range(h_):
            s = kk[:, h // g] @ np.asarray(q)[b, h] / math.sqrt(d_)
            p = np.exp(s - s.max())
            p /= p.sum()
            o[b, h] = p @ vv[:, h // g]
    return o


@pytest.mark.parametrize("kvh", [4, 2], ids=["mha", "gqa"])
def test_masked_decode_attention_matches_reference(kvh):
    rng = np.random.RandomState(0)
    B, H, D, L = 2, 4, 64, 256
    q = jnp.asarray(rng.rand(B, H, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, L, kvh, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, L, kvh, D).astype(np.float32))
    lens = jnp.asarray([100, 256], jnp.int32)
    out = masked_decode_attention(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out), _ref_decode(q, k, v, lens),
                               rtol=2e-5, atol=2e-6)


def test_paged_attention_scattered_tables():
    rng = np.random.RandomState(1)
    B, H, KVH, D = 2, 4, 4, 64
    PAGE, NPAGES = 32, 16
    q = jnp.asarray(rng.rand(B, H, D).astype(np.float32))
    k_pages = jnp.asarray(rng.rand(NPAGES, PAGE, KVH, D).astype(np.float32))
    v_pages = jnp.asarray(rng.rand(NPAGES, PAGE, KVH, D).astype(np.float32))
    tables = jnp.asarray([[3, 7, 1, 0], [9, 2, 15, 4]], jnp.int32)
    lens = jnp.asarray([100, 70], jnp.int32)
    out = paged_attention(q, k_pages, v_pages, tables, lens)

    o = np.zeros((B, H, D), np.float32)
    for b in range(B):
        kk = np.concatenate(
            [np.asarray(k_pages)[p] for p in np.asarray(tables)[b]],
            0)[:int(lens[b])]
        vv = np.concatenate(
            [np.asarray(v_pages)[p] for p in np.asarray(tables)[b]],
            0)[:int(lens[b])]
        for h in range(H):
            s = kk[:, h] @ np.asarray(q)[b, h] / math.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            o[b, h] = p @ vv[:, h]
    np.testing.assert_allclose(np.asarray(out), o, rtol=2e-5, atol=2e-6)


def test_paged_cache_update_scatters_tokens():
    from paddle_tpu.models import PagedKVCache

    cache = PagedKVCache(batch=2, max_len=64, kv_heads=2, head_dim=8,
                         page_size=32)
    k = jnp.ones((2, 3, 2, 8))
    cache.update(k, 2 * k)
    assert cache.length == 3
    # pages are interleaved: page 0 of seq 0 is pool slot 0, seq 1 slot 1
    np.testing.assert_array_equal(np.asarray(cache.tables), [[0, 2], [1, 3]])
    assert float(cache.k_pages[0, 2, 0, 0]) == 1.0  # token 2 of seq 0
    assert float(cache.k_pages[1, 2, 0, 0]) == 1.0  # token 2 of seq 1
    assert float(cache.k_pages[0, 3, 0, 0]) == 0.0  # beyond length
    assert float(cache.v_pages[1, 1, 1, 3]) == 2.0


def _gen(cache_kind, flag_on):
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny_config
    from paddle_tpu.models.generation import generate

    paddle.set_flags({"FLAGS_use_pallas_kernels": flag_on})
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny_config()).eval()
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 256, (2, 12)).astype(np.int32))
        out = generate(model, ids, max_new_tokens=6, cache=cache_kind)
        return np.asarray(out._value)
    finally:
        paddle.set_flags({"FLAGS_use_pallas_kernels": True})


def test_generation_equivalent_across_cache_paths():
    """The Pallas decode kernels and cache layouts must not change tokens:
    static+kernel == static+jnp == paged+kernel."""
    base = _gen("static", False)   # masked jnp composition
    static_k = _gen("static", True)  # masked_decode_attention kernel
    paged_k = _gen("paged", True)    # paged_attention kernel
    np.testing.assert_array_equal(base, static_k)
    np.testing.assert_array_equal(base, paged_k)
