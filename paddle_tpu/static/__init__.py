"""paddle_tpu.static — static-graph compatibility surface.

The reference's static mode (Program/Executor, python/paddle/static/) is
absorbed by jit tracing on TPU (SURVEY.md §7: PirInterpreter ← XLA). What
remains meaningful is the declarative bits: ``InputSpec`` (trace
signatures), and save/load_inference_model (paddle_tpu.jit.save/load over
StableHLO artifacts).
"""
from __future__ import annotations

import numpy as np

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


class InputSpec:
    """Reference python/paddle/static/input.py InputSpec: shape with None
    for dynamic dims (exported as symbolic dims), dtype, name."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype!r}, "
                f"name={self.name!r})")

    def to_aval(self):
        import jax

        from ..core.dtype import to_jax_dtype

        shape = tuple(1 if d is None or d < 0 else d for d in self.shape)
        return jax.ShapeDtypeStruct(shape, to_jax_dtype(self.dtype))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    raise NotImplementedError(
        "program-based save_inference_model is absorbed by paddle_tpu.jit.save "
        "(StableHLO export); use jit.save(layer, path, input_spec=[...])")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "use paddle_tpu.jit.load / paddle_tpu.inference.create_predictor")
