"""paddle.audio.features — feature-extraction layers (reference
python/paddle/audio/features/layers.py). Implemented in audio/__init__;
re-exported here for namespace parity."""
from . import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram  # noqa: F401

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
