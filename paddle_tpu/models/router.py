"""Replica serving fleet: health-gated routing, bit-exact failover,
elastic membership — the tier in front of N ``ServingFrontend`` replicas.

One chip's engine saturates at its slot count; the "millions of users"
architecture is a ROUTER fronting N replicas, built so a replica dying
mid-decode costs one retry, not a lost request:

* **Load-aware dispatch** — each admission is scored against every
  eligible replica's ``health()`` snapshot (queue depth, queued-token
  backlog, in-flight KV slots) and lands on the least-loaded one.
* **Health gating** — a replica is routed around when its router-side
  ``CircuitBreaker`` is open (tripped by failed results or out-of-band
  death evidence), its frontend stopped admitting, or — with a gang
  store — its fleet heartbeat lapsed: a ``PeerFailureDetector``
  (``distributed/gang.py``) sweeping the CURRENT membership marks it
  dead within one ``FLAGS_heartbeat_ttl`` lease.
* **Bit-exact failover** — every engine samples from per-request key
  streams that are a pure function of ``(engine seed, rid, token
  index)``, and the router owns the rid space. A request stranded on a
  failed replica is resubmitted to a healthy one as ``original prompt +
  tokens already emitted`` with ``token_base = len(emitted)`` — the
  continuation is token-identical to the uninterrupted run, whether the
  replay starts from token 0 (replica died, partials unknown) or
  mid-stream (replica retired it ``failed`` with partial output). The
  contract requires every replica to serve the same weights with the
  same engine seed/sampling config (checked at registration, mismatches
  are logged and counted).
* **Hedging** — a tail-latency-sensitive ``submit(hedge=True)`` runs on
  the two best replicas at once; the first terminal result wins and the
  loser is cancelled. Determinism makes the copies token-identical, so
  whichever finishes first is THE answer.
* **Elastic membership** — ``scale_out()`` admits a replica after
  warmup; ``scale_in()`` drains it (``shutdown(drain=True)``: in-flight
  requests finish, queued ones are requeued onto the survivors) before
  deregistering its store presence and heartbeat. Replica processes run
  under the ``launch()`` supervisor with ``restart_policy="worker"``
  (:func:`launch_fleet`): a crashed replica is respawned alone, within
  the supervisor's restart budget, while the survivors keep serving.

The router is a synchronous pump like the frontend: ``submit()`` as
requests arrive, ``step()`` to make progress, ``results(wait=True)`` to
drain. Terminal statuses mirror the frontend's; the retirement switch
(``_RETIREMENT``) is CI-gated to cover every status a replica can emit
(tests/test_no_bare_except.py).
"""
from __future__ import annotations

import contextlib
import itertools
import time

import numpy as np

from ..core.resilience import CircuitBreaker, Deadline, bump_counter, logger
from .frontend import RequestResult

__all__ = ["ServingRouter", "launch_fleet"]


class _Replica:
    """One registered replica: frontend + router-side health state."""

    __slots__ = ("id", "frontend", "breaker", "state", "hb", "assigned",
                 "probes", "served")

    def __init__(self, rep_id, frontend, breaker):
        self.id = rep_id
        self.frontend = frontend
        self.breaker = breaker
        self.state = "up"            # up | draining | dead
        self.hb = None               # store heartbeat handle
        self.assigned: set = set()   # rids currently pending here
        self.probes: set = set()     # rids riding a half-open probe slot
        self.served = 0


class _FleetRequest:
    """Router-side record of one client request across failovers."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "priority", "deadline",
                 "emitted", "live", "excluded", "failovers", "hedged")

    def __init__(self, rid, prompt, max_new_tokens, priority, deadline,
                 hedged):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.deadline = deadline
        self.emitted = np.zeros((0,), np.int32)  # tokens delivered by
        #                                          failed/drained attempts
        self.live: set = set()       # replica ids where rid is pending
        self.excluded: set = set()   # replicas this rid must avoid
        self.failovers = 0
        self.hedged = bool(hedged)


class ServingRouter:
    """Health-gated, failover-capable router over ``ServingFrontend``
    replicas.

    Usage::

        router = ServingRouter(max_failovers=3)
        router.add_replica(make_frontend())     # N times (or scale_out)
        rid = router.submit(prompt, max_new_tokens=64)
        for rid, res in router.results(wait=True).items():
            print(rid, res.status, res.tokens)

    With a gang ``store``, replicas heartbeat under
    ``{fleet_prefix}/hb`` and a ``PeerFailureDetector`` sweeping the
    current membership routes around a silent death within one lease —
    the same machinery a multi-process fleet under ``launch()`` uses.
    """

    def __init__(self, max_failovers=3, hedge=False,
                 default_max_new_tokens=64, token_unit=64,
                 store=None, fleet_prefix="fleet", lease=None,
                 heartbeat_interval=None, breaker_threshold=3,
                 breaker_cooldown_s=30.0):
        from ..core.flags import flag

        self.max_failovers = int(max_failovers)
        self.hedge_default = bool(hedge)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.token_unit = float(token_unit)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self._replicas: dict[int, _Replica] = {}
        self._requests: dict[int, _FleetRequest] = {}
        self._results: dict[int, RequestResult] = {}
        self._parked: list[int] = []
        self._rids = itertools.count()
        self._rep_ids = itertools.count()
        self._engine_fingerprint = None
        # fleet store (optional): membership keys + replica heartbeats +
        # the lease-based failure detector
        self._store = store
        self._prefix = fleet_prefix
        self._lease = float(lease if lease is not None
                            else flag("FLAGS_heartbeat_ttl"))
        self._hb_interval = float(heartbeat_interval if heartbeat_interval
                                  is not None else max(self._lease / 3, 0.05))
        self._detector = None
        if store is not None:
            from ..distributed.gang import GangContext, PeerFailureDetector

            ctx = GangContext(store, rank=-1, world_size=0)
            self._detector = PeerFailureDetector(
                ctx, lease=self._lease, interval=self._hb_interval,
                prefix=f"{fleet_prefix}/hb",
                ranks=self._member_ids).start(beat=False)
        # dispatch-overhead accounting: router bookkeeping vs time inside
        # replica frontends (the acceptance gate records
        # fleet_router_overhead_pct = route_s / wall)
        self._route_s = 0.0
        self._pump_s = 0.0
        self._counts: dict[str, int] = {}
        self._t0 = time.monotonic()

    # -------------------------------------------------------- membership

    def _member_ids(self):
        return [r.id for r in self._replicas.values() if r.state == "up"]

    def _fingerprint(self, frontend):
        eng = frontend.engine
        return (eng._seed, eng.do_sample, eng.temperature, eng.top_k,
                eng.top_p, eng.eos_token_id)

    def add_replica(self, frontend, replica_id=None, warmup=False):
        """Register a replica (its frontend must already be started).
        Returns the replica id. With a fleet store, the replica's
        membership key is published and its heartbeat starts — silent
        death is then detected by lease, not by a failed dispatch."""
        rep_id = (next(self._rep_ids) if replica_id is None
                  else int(replica_id))
        while replica_id is None and rep_id in self._replicas:
            rep_id = next(self._rep_ids)
        if rep_id in self._replicas:
            raise ValueError(f"replica id {rep_id} already registered")
        fp = self._fingerprint(frontend)
        if self._engine_fingerprint is None:
            self._engine_fingerprint = fp
        elif fp != self._engine_fingerprint:
            # a mismatched seed/sampling config silently breaks the
            # bit-exact failover contract — loud, counted, but admitted
            # (the operator may be doing a deliberate config rollout)
            bump_counter("fleet.config_mismatch")
            logger.warning(
                "replica %d engine config %r differs from the fleet's %r; "
                "failover replays will NOT be bit-exact", rep_id, fp,
                self._engine_fingerprint)
        if warmup:
            frontend.warmup()
        rep = _Replica(rep_id, frontend, CircuitBreaker(
            f"fleet.replica.{rep_id}",
            failure_threshold=self.breaker_threshold,
            cooldown_s=self.breaker_cooldown_s))
        if self._store is not None:
            self._store.set(f"{self._prefix}/member/{rep_id}", b"up")
            rep.hb = self._store.register_heartbeat(
                rep_id, self._hb_interval, prefix=f"{self._prefix}/hb")
        self._replicas[rep_id] = rep
        bump_counter("fleet.replica_up")
        self._route_parked()
        return rep_id

    def scale_out(self, frontend, replica_id=None, warmup=True):
        """Grow the fleet: warm the replica's compiled shapes FIRST (a
        cold replica would absorb compile time into live requests), then
        admit it and immediately route parked/backlogged work there."""
        bump_counter("fleet.scale_out")
        return self.add_replica(frontend, replica_id=replica_id,
                                warmup=warmup)

    def scale_in(self, replica_id):
        """Shrink the fleet gracefully: stop routing to the replica,
        drain it (in-flight requests FINISH and deliver normally; queued
        ones are requeued onto the survivors with their budgets intact),
        then deregister its membership and heartbeat."""
        rep = self._replicas[replica_id]
        rep.state = "draining"
        bump_counter("fleet.scale_in")
        rep.frontend.shutdown(drain=True)
        self._collect(rep)
        self._deregister(rep)
        del self._replicas[replica_id]
        self._route_parked()

    def _deregister(self, rep):
        if rep.hb is not None:
            with contextlib.suppress(Exception):
                rep.hb.stop(self._hb_interval + 1)
            rep.hb = None
        if self._store is not None:
            # membership + beat keys must not linger: a deliberate leave
            # is not a death, and the next sweep must not see a stale beat
            with contextlib.suppress(Exception):
                self._store.delete_key(f"{self._prefix}/member/{rep.id}")
            with contextlib.suppress(Exception):
                self._store.delete_heartbeat(rep.id,
                                             prefix=f"{self._prefix}/hb")

    def fail_replica(self, replica_id, reason="operator kill"):
        """Declare a replica dead NOW (fault drills / orchestrator
        signal): trip its breaker, deregister it, and fail over every
        request stranded there."""
        rep = self._replicas.get(replica_id)
        if rep is not None:
            self._kill_replica(rep, reason)

    def _kill_replica(self, rep, reason):
        if rep.state == "dead":
            return
        rep.state = "dead"
        rep.breaker.trip()
        bump_counter("fleet.replica_dead")
        logger.warning("replica %d marked dead (%s); failing over %d "
                       "stranded request(s)", rep.id, reason,
                       len(rep.assigned))
        # salvage results the replica already retired before it broke —
        # a terminal verdict that exists must not be recomputed
        with contextlib.suppress(Exception):
            self._collect(rep)
        self._deregister(rep)
        for rid in list(rep.assigned):
            rep.assigned.discard(rid)
            freq = self._requests.get(rid)
            if freq is None:
                continue
            freq.live.discard(rep.id)
            freq.excluded.add(rep.id)
            if freq.live:
                continue  # a hedge copy is still running elsewhere
            self._failover(freq, None, f"replica {rep.id} dead: {reason}")

    # --------------------------------------------------------- dispatch

    def _score(self, h):
        """Load score from one health snapshot — lower is better. The
        three load signals share a scale by normalizing the token
        backlog to ``token_unit`` (≈ one request's decode budget)."""
        return (h["queue_depth"] + h["active_slots"]
                + h["queued_tokens"] / self.token_unit)

    def _candidates(self, freq):
        """Eligible replicas for this request, best (least loaded)
        first. Closed-breaker replicas are preferred; half-open ones are
        used only when no closed one is eligible, and routing there
        consumes the breaker's probe slot (the request IS the probe)."""
        closed, half_open = [], []
        for rep in list(self._replicas.values()):
            if rep.state != "up" or rep.id in freq.excluded:
                continue
            if rep.id in freq.live:
                # a copy of this rid is already pending there (hedge arm
                # or a not-yet-collected attempt) — resubmitting the same
                # rid to that frontend would raise
                continue
            state = rep.breaker.state()
            if state == CircuitBreaker.OPEN:
                continue
            try:
                h = rep.frontend.health()
            except Exception as e:  # a broken health probe is a death
                self._kill_replica(rep, f"health() raised: {e!r}")
                continue
            if not h["ready"]:
                continue
            (closed if state == CircuitBreaker.CLOSED
             else half_open).append((self._score(h), rep.id))
        pool = sorted(closed) or sorted(half_open)
        return pool

    def _submit_to(self, freq, rep_id):
        rep = self._replicas[rep_id]
        probe = rep.breaker.state() == CircuitBreaker.HALF_OPEN
        if probe and not rep.breaker.allow():
            return False
        k = len(freq.emitted)
        prompt = (np.concatenate([freq.prompt, freq.emitted])
                  if k else freq.prompt)
        rep.frontend.submit(prompt, freq.max_new_tokens - k,
                            priority=freq.priority,
                            deadline_s=freq.deadline, rid=freq.rid,
                            token_base=k)
        rep.assigned.add(freq.rid)
        freq.live.add(rep_id)
        if probe:
            rep.probes.add(freq.rid)
        return True

    def _dispatch(self, freq):
        pool = self._candidates(freq)
        sent = False
        for _, rep_id in pool:
            if self._submit_to(freq, rep_id):
                sent = True
                break
        if sent and freq.hedged:
            for _, rep_id in pool:
                if rep_id not in freq.live and self._submit_to(freq,
                                                               rep_id):
                    bump_counter("fleet.hedged")
                    break
        return sent

    def _failover(self, freq, partial_tokens, reason, charge=True):
        """Resubmit a stranded request. ``partial_tokens`` (if the failed
        attempt surfaced any) extend the emitted prefix so the replay
        resumes mid-stream instead of recomputing; determinism makes the
        continuation bit-identical either way."""
        if partial_tokens is not None and len(partial_tokens):
            freq.emitted = np.concatenate(
                [freq.emitted, np.asarray(partial_tokens, np.int32)])
        if len(freq.emitted) >= freq.max_new_tokens:
            # the failed attempt had in fact finished the budget — the
            # emitted prefix IS the answer
            self._deliver(freq, "ok", freq.emitted, reason)
            return
        if charge:
            freq.failovers += 1
        if freq.failovers > self.max_failovers:
            bump_counter("fleet.failover_budget_exhausted")
            self._deliver(freq, "failed", freq.emitted,
                          f"failover budget exhausted ({reason})")
            return
        bump_counter("fleet.failover")
        if not self._dispatch(freq):
            if freq.rid not in self._parked:
                self._parked.append(freq.rid)

    def _route_parked(self):
        for rid in list(self._parked):
            freq = self._requests.get(rid)
            if freq is None:
                with contextlib.suppress(ValueError):
                    self._parked.remove(rid)
                continue
            if freq.deadline.expired():
                self._deliver(freq, "timed_out", freq.emitted,
                              "expired while parked at the router")
                continue
            if self._dispatch(freq):
                self._parked.remove(rid)
                continue
            ups = [r for r in self._replicas.values() if r.state == "up"]
            if ups and all(r.id in freq.excluded for r in ups):
                # every live replica already failed this request
                self._deliver(freq, "failed", freq.emitted,
                              "every live replica excluded by failover")

    # ------------------------------------------------------ client API

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_s=None, hedge=None) -> int:
        """Admit one request to the fleet; returns its rid. The verdict
        lands in ``results()``. ``hedge=True`` (or the router-wide
        default) duplicates the request onto the two least-loaded
        replicas; the first terminal result wins."""
        rid = next(self._rids)
        prompt = np.asarray(prompt).astype(np.int32).ravel()
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        deadline = (deadline_s if isinstance(deadline_s, Deadline)
                    else Deadline(deadline_s))
        freq = _FleetRequest(rid, prompt, max_new, priority, deadline,
                             self.hedge_default if hedge is None else hedge)
        self._requests[rid] = freq
        t0 = time.monotonic()
        if not self._dispatch(freq):
            self._parked.append(rid)
            bump_counter("fleet.parked")
        self._route_s += time.monotonic() - t0
        return rid

    def cancel(self, rid) -> bool:
        """Cancel a request wherever it lives (parked or on replicas).
        Partial tokens an in-flight copy already produced are preserved
        in the delivered result (same contract as
        ``ServingFrontend.cancel``)."""
        freq = self._requests.get(rid)
        if freq is None:
            return False
        for rep_id in list(freq.live):
            rep = self._replicas.get(rep_id)
            if rep is None or rep.state != "up":
                continue
            # frontend.cancel records a "cancelled" result carrying the
            # partial tokens; collecting it routes through the normal
            # retirement switch, which delivers emitted + partials
            with contextlib.suppress(Exception):
                rep.frontend.cancel(rid)
            self._collect(rep)
            if rid not in self._requests:
                return True
        self._deliver(freq, "cancelled", freq.emitted,
                      "cancelled by caller")
        return True

    def pending(self) -> int:
        return len(self._requests)

    def step(self):
        """One fleet turn: sweep liveness (lease-based death detection),
        route parked work, pump every live replica one scheduler turn,
        and run the retirement switch over everything that finished."""
        t_start = time.monotonic()
        self._sweep_liveness()
        self._route_parked()
        pump = 0.0
        for rep in list(self._replicas.values()):
            if rep.state != "up":
                continue
            t0 = time.monotonic()
            try:
                if rep.frontend.pending() or rep.frontend.engine.has_work():
                    rep.frontend.step()
            except Exception as e:  # replica broke mid-dispatch
                pump += time.monotonic() - t0
                self._kill_replica(rep, f"step() raised: {e!r}")
                continue
            pump += time.monotonic() - t0
            self._collect(rep)
        self._route_parked()
        self._route_s += (time.monotonic() - t_start) - pump
        self._pump_s += pump

    def results(self, wait=False, timeout_s=None) -> dict:
        """Pop terminal results as ``{rid: RequestResult}``. With
        ``wait=True`` the router pumps until every pending request
        resolves, the fleet has no live replica left (remaining requests
        deliver ``unavailable``), or ``timeout_s`` expires (remaining
        deliver ``timed_out``)."""
        if wait:
            deadline = Deadline(timeout_s)
            while self._requests:
                if not any(r.state == "up"
                           for r in self._replicas.values()):
                    for freq in list(self._requests.values()):
                        self._deliver(freq, "unavailable", freq.emitted,
                                      "no live replica")
                    break
                if deadline.expired():
                    for freq in list(self._requests.values()):
                        self._deliver(freq, "timed_out", freq.emitted,
                                      "results(wait) timeout")
                    break
                self.step()
        out, self._results = self._results, {}
        return out

    # ------------------------------------------------------- retirement

    # status -> handler; CI-gated (tests/test_no_bare_except.py) to cover
    # every terminal state a frontend result can carry, so a new engine
    # status cannot silently fall through the switch
    _RETIREMENT = {
        "ok": "_retire_ok",
        "failed": "_retire_failed",
        "timed_out": "_retire_timed_out",
        "cancelled": "_retire_cancelled",
        "rejected": "_retire_rejected",
        "unavailable": "_retire_unavailable",
    }

    def _collect(self, rep):
        for rid, res in rep.frontend.results().items():
            rep.assigned.discard(rid)
            rep.probes.discard(rid)
            freq = self._requests.get(rid)
            if freq is None:
                continue  # already delivered (hedge loser, late cancel)
            freq.live.discard(rep.id)
            handler = self._RETIREMENT.get(res.status)
            if handler is None:
                # unreachable when the CI guard holds; deliver verbatim
                # rather than dropping the request on the floor
                bump_counter("fleet.unknown_terminal")
                self._deliver(freq, res.status, res.tokens, res.reason)
                continue
            getattr(self, handler)(rep, freq, res)

    def _note_verdict(self, rep, rid, ok):
        if ok:
            rep.breaker.record_success()
        else:
            rep.breaker.record_failure()
        rep.probes.discard(rid)

    def _retire_ok(self, rep, freq, res):
        self._note_verdict(rep, freq.rid, ok=True)
        rep.served += 1
        tokens = (np.concatenate([freq.emitted, res.tokens])
                  if len(freq.emitted) else res.tokens)
        self._deliver(freq, "ok", tokens, res.reason)

    def _retire_failed(self, rep, freq, res):
        self._note_verdict(rep, freq.rid, ok=False)
        # exclude UNCONDITIONALLY: even when a hedge copy survives, a
        # later failover must not land back on the replica that already
        # failed this exact rid
        freq.excluded.add(rep.id)
        if freq.live:
            bump_counter("fleet.hedge_arm_failed")
            return  # the surviving hedge copy is the failover
        self._failover(freq, res.tokens,
                       f"replica {rep.id} failed it: {res.reason}")

    def _retire_timed_out(self, rep, freq, res):
        # the deadline is the CLIENT's budget: replaying elsewhere cannot
        # win back wall time that is already spent
        tokens = (np.concatenate([freq.emitted, res.tokens])
                  if len(freq.emitted) else res.tokens)
        self._deliver(freq, "timed_out", tokens, res.reason)

    def _retire_cancelled(self, rep, freq, res):
        if rep.state != "up":
            # a draining/dead replica handing the request back is not a
            # client cancel: requeue it (budget intact — no charge). A
            # surviving hedge copy IS the requeue — drop this arm.
            if freq.live:
                bump_counter("fleet.hedge_arm_dropped")
                return
            self._failover(freq, res.tokens,
                           f"replica {rep.id} drained", charge=False)
            return
        tokens = (np.concatenate([freq.emitted, res.tokens])
                  if len(freq.emitted) else res.tokens)
        self._deliver(freq, "cancelled", tokens, res.reason)

    def _retire_rejected(self, rep, freq, res):
        # the replica's admission control shed it; another replica may
        # have room (malformed requests reject everywhere and exhaust
        # the budget quickly)
        freq.excluded.add(rep.id)
        if freq.live:
            return
        self._failover(freq, None,
                       f"replica {rep.id} rejected it: {res.reason}")

    def _retire_unavailable(self, rep, freq, res):
        # the replica's own breaker refused it — evidence for the
        # router's breaker too, then reroute
        self._note_verdict(rep, freq.rid, ok=False)
        freq.excluded.add(rep.id)
        if freq.live:
            return
        self._failover(freq, None, f"replica {rep.id} unavailable")

    def _deliver(self, freq, status, tokens=None, reason=None):
        self._results[freq.rid] = RequestResult(
            freq.rid, status, tokens, reason)
        self._counts[status] = self._counts.get(status, 0) + 1
        self._requests.pop(freq.rid, None)
        with contextlib.suppress(ValueError):
            self._parked.remove(freq.rid)
        for rep_id in list(freq.live):
            rep = self._replicas.get(rep_id)
            if rep is None:
                continue
            rep.assigned.discard(freq.rid)
            if freq.rid in rep.probes:
                # this copy resolves with no verdict on the replica:
                # free the half-open probe slot it was riding
                rep.probes.discard(freq.rid)
                rep.breaker.release_probe()
            if rep.state == "up":
                with contextlib.suppress(Exception):
                    rep.frontend.cancel(freq.rid)
        freq.live.clear()

    # --------------------------------------------------- liveness sweep

    def _sweep_liveness(self):
        if self._detector is None:
            return
        for rep_id in self._detector.dead_peers():
            rep = self._replicas.get(rep_id)
            if rep is not None and rep.state == "up":
                self._kill_replica(
                    rep, f"heartbeat lease ({self._lease:g}s) expired")

    # ------------------------------------------------------------ admin

    def warmup(self, cache_dir=None):
        """AOT-warm every replica's compiled serving shapes."""
        return {rep.id: rep.frontend.warmup(cache_dir=cache_dir)
                for rep in self._replicas.values() if rep.state == "up"}

    def shutdown(self, drain=True):
        """Drain (or hard-stop) every replica and deliver what resolves;
        anything still pending afterwards delivers ``unavailable``."""
        for rep in list(self._replicas.values()):
            if rep.state == "up":
                with contextlib.suppress(Exception):
                    rep.frontend.shutdown(drain=drain)
                rep.state = "draining"
                self._collect(rep)
            self._deregister(rep)
        for freq in list(self._requests.values()):
            self._deliver(freq, "unavailable", freq.emitted,
                          "fleet shutdown")
        self._replicas.clear()

    def health(self) -> dict:
        """Fleet-level snapshot: per-replica health + aggregate load."""
        reps = {}
        for rep in self._replicas.values():
            try:
                h = rep.frontend.health() if rep.state == "up" else {}
            except Exception:
                h = {}
            reps[rep.id] = {"state": rep.state,
                            "breaker": rep.breaker.state(),
                            "assigned": len(rep.assigned), **h}
        up = [r for r in self._replicas.values() if r.state == "up"]
        return {
            "replicas": reps,
            "up": len(up),
            "total": len(self._replicas),
            "pending": len(self._requests),
            "parked": len(self._parked),
            "ready": bool(up),
        }

    def stats(self) -> dict:
        """Router-side accounting. ``router_overhead_pct`` is the share
        of ACTIVE request-processing time spent in routing/bookkeeping
        outside the replica frontends — ``route_s / (route_s + pump_s)``,
        deliberately NOT route/wall: wall includes warmup and idle time,
        which would let an arbitrarily slow routing path pass the gate.
        The fleet acceptance gate records it as
        ``fleet_router_overhead_pct`` (< 5%)."""
        wall = time.monotonic() - self._t0
        active = self._route_s + self._pump_s
        return {
            "wall_s": wall,
            "route_s": self._route_s,
            "pump_s": self._pump_s,
            "router_overhead_pct": (100.0 * self._route_s / active
                                    if active > 0 else 0.0),
            "replicas_up": sum(1 for r in self._replicas.values()
                               if r.state == "up"),
            "served_by_replica": {r.id: r.served
                                  for r in self._replicas.values()},
            **{f"requests_{k}": v for k, v in sorted(self._counts.items())},
        }


def launch_fleet(entry, n_replicas, entry_args=(), max_restarts=3,
                 **launch_kwargs):
    """Run ``entry`` as ``n_replicas`` replica worker processes under the
    ``launch()`` supervisor with the serving failure domain:
    ``restart_policy="worker"`` (a crashed replica respawns ALONE within
    the restart budget while the survivors keep serving) and the
    supervisor's gang store exported for fleet heartbeats."""
    from ..distributed.launch import launch

    return launch(entry, entry_args=entry_args,
                  nproc_per_node=n_replicas, max_restarts=max_restarts,
                  restart_policy="worker", **launch_kwargs)
