"""Distributed checkpoint: sharded save + reshard-on-load, multi-host safe.

Analog of /root/reference/python/paddle/distributed/checkpoint/
(save_state_dict.py, load_state_dict.py, metadata.py): per-rank ``.distcp``
shard files + metadata mapping each tensor to
(global_shape, dtype, per-shard global offsets), with cross-rank dedup of
replicated tensors (dedup_tensor:117) and reshard-on-load across different
meshes/degrees (ReadItem planning, load_state_dict.py:41).

Multi-host discipline — the two reference invariants this file preserves:

* **save never materializes a global tensor.** Each process writes only its
  *addressable* shards (``jax.Array.addressable_shards``), deduped by
  ``replica_id == 0`` — exactly one process writes each replicated piece,
  like the reference's ``dedup_tensor``. Per-dim global offsets come from
  each shard's ``.index``, so sharding along ANY dim (or several) is
  recorded faithfully. Each rank also writes its own
  ``{rank}.metadata.json`` — no cross-rank gather at save time.
* **load plans per-shard reads.** For every addressable shard of the
  *destination* layout, the loader computes which saved pieces overlap its
  global index box (the ReadItem plan), reads only those entries, assembles
  the local block, and builds the global array with
  ``jax.make_array_from_single_device_arrays`` — each host touches only
  the bytes its devices need, so save-dp2 → load-dp4 (or any other
  degree/mesh change) reshards on the fly.
"""
from __future__ import annotations

import json
import os
import re
import zlib

import numpy as np

from ..core.resilience import CheckpointCorruptionError, inject, logger
from ..core.tensor import Tensor
from ..framework.io import save_arrays

__all__ = [
    "save_state_dict", "load_state_dict", "CheckpointCorruptionError",
    "save_snapshot", "load_latest_snapshot", "latest_complete_snapshot",
    "commit_snapshot", "committed_step",
]


def _gang_rank():
    """This process's rank in the GANG. Under real multi-controller jax
    that is ``jax.process_index()``; under the multi-process launcher
    WITHOUT ``jax.distributed`` every worker is process 0 of its own
    runtime, so the launcher's ``PADDLE_TRAINER_ID`` is authoritative —
    otherwise peers would all write ``0.distcp`` and race to prune the
    same directories."""
    import jax

    if jax.process_count() > 1:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID",
                              jax.process_index()) or 0)


def _gang_world():
    import jax

    if jax.process_count() > 1:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", "1") or 1)


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _atomic_json(obj, path):
    # per-process tmp name: gang ranks sharing a directory may write the
    # same json (identical content) concurrently, and a shared tmp name
    # makes one rank's os.replace yank the other's file mid-commit
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _index_to_offsets(index, shape):
    """A shard's ``.index`` (tuple of slices into the global array) as
    concrete per-dim [start, stop)."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _is_jax_array(v):
    import jax

    return isinstance(v, jax.Array)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, num_shards=None, async_save=False,
                    gang_layout=False):
    """Write ``state_dict`` as a sharded checkpoint directory: this
    process's addressable shards + this process's metadata.

    ``num_shards``/``async_save`` are accepted for reference-API parity but
    ignored: file parallelism is one file per process (the reference's
    per-rank ``.distcp`` layout), and saving is synchronous.

    ``gang_layout=True`` is for launcher gangs writing into ONE SHARED
    directory (``fit(elastic=True)``): shard files and metadata are named
    by the GANG rank (``PADDLE_TRAINER_ID``) instead of
    ``jax.process_index()`` — under the multi-process launcher without
    ``jax.distributed`` every worker is process 0 of its own runtime and
    would otherwise collide on ``0.distcp``. It must stay off (default)
    for per-host directories: in gang layout non-zero ranks write only a
    completion marker, which is the wrong thing on a disk rank 0 never
    sees. Under real multi-controller jax the two layouts coincide.
    """
    import jax

    os.makedirs(path, exist_ok=True)
    rank = _gang_rank() if gang_layout else jax.process_index()
    fname = f"{rank}.distcp"
    local: dict[str, np.ndarray] = {}
    # world_size lets load ignore stale higher-rank files left behind by an
    # earlier save into the same directory from a larger world
    meta = {"tensors": {}, "version": 2,
            "world_size": (_gang_world() if gang_layout
                           else jax.process_count())}

    # In gang layout WITHOUT jax.distributed each worker is a full
    # single-process runtime: every tensor is a fully addressable replica
    # on every gang rank. One writer (gang rank 0) records them — N ranks
    # writing full copies into one shared directory would alias every
    # byte N times (the reference's dedup_tensor rule, applied at gang
    # granularity). Non-zero ranks still commit their (possibly empty)
    # shard + metadata files, which is exactly the per-rank completion
    # marker the commit protocol checks.
    gang_replicated = (gang_layout and jax.process_count() == 1
                       and _gang_world() > 1)

    for key, v in state_dict.items():
        if gang_replicated and rank != 0:
            continue
        if isinstance(v, Tensor):
            v = v._value
        if _is_jax_array(v) and v.ndim > 0:
            entry = {"shape": list(v.shape), "dtype": np.dtype(v.dtype).name,
                     "shards": []}
            for j, sh in enumerate(v.addressable_shards):
                if sh.replica_id != 0:
                    continue  # dedup: one writer per replicated piece
                data = np.asarray(sh.data)
                skey = f"{key}@{rank}.{j}"
                local[skey] = data
                entry["shards"].append({
                    "key": skey, "file": fname,
                    "offsets": _index_to_offsets(sh.index, v.shape),
                    "crc32": _crc32(data),
                })
            if entry["shards"]:
                meta["tensors"][key] = entry
        elif _is_jax_array(v) and getattr(v, "committed", False):
            # 0-d scalar COMMITTED to a mesh (loss scale, step counter):
            # np.asarray could throw under multi-host — the lowest-rank
            # owner reads its local replica shard and writes. The
            # `committed` flag is the same on every rank (SPMD placement
            # code), unlike is_fully_addressable, so all ranks agree on
            # the branch; host-created scalars (committed=False) take the
            # coordinator branch below. Exactly one writer either way.
            owners = {d.process_index for d in v.sharding.device_set}
            if rank == min(owners):
                arr = np.asarray(v.addressable_shards[0].data)
                skey = f"{key}@{rank}.0"
                local[skey] = arr
                meta["tensors"][key] = {
                    "shape": list(arr.shape), "dtype": arr.dtype.name,
                    "shards": [{"key": skey, "file": fname,
                                "offsets": [[0, s] for s in arr.shape],
                                "crc32": _crc32(arr)}],
                }
        elif rank == coordinator_rank:
            # host scalars / plain arrays: identical on every rank, the
            # coordinator writes them
            arr = np.asarray(v)
            skey = f"{key}@{rank}.0"
            local[skey] = arr
            meta["tensors"][key] = {
                "shape": list(arr.shape), "dtype": arr.dtype.name,
                "shards": [{"key": skey, "file": fname,
                            "offsets": [[0, s] for s in arr.shape],
                            "crc32": _crc32(arr)}],
            }

    # crash safety: write payload + metadata to *.tmp, then atomically
    # rename — a process killed mid-save leaves stale tmp files, never a
    # half-written shard that a later load would read
    shard_path = os.path.join(path, fname)
    save_arrays(local, shard_path + ".tmp")
    inject("ckpt_commit")  # simulated crash BETWEEN write and rename
    os.replace(shard_path + ".tmp", shard_path)
    _atomic_json(meta, os.path.join(path, f"{rank}.metadata.json"))


def _merged_metadata(path):
    first = os.path.join(path, "0.metadata.json")
    if not os.path.exists(first):
        if os.path.exists(os.path.join(path, "metadata.json")):
            raise ValueError(
                f"checkpoint at {path} uses the legacy v1 single-metadata "
                "format, which this version no longer reads; re-save it")
        raise FileNotFoundError(f"no 0.metadata.json under {path}")
    with open(first) as f:
        meta0 = json.load(f)
    world = int(meta0.get("world_size", 1))
    # merge exactly ranks [0, world): stale higher-rank files from an older,
    # larger-world save into this directory are ignored
    files = [os.path.join(path, f"{r}.metadata.json") for r in range(world)]
    missing = [fp for fp in files if not os.path.exists(fp)]
    if missing:
        raise FileNotFoundError(
            f"checkpoint at {path} saved from {world} processes is missing "
            f"metadata files: {missing}")
    tensors: dict[str, dict] = {}
    for fp in files:
        with open(fp) as f:
            meta = json.load(f)
        for key, entry in meta["tensors"].items():
            if key in tensors:
                tensors[key]["shards"].extend(entry["shards"])
            else:
                tensors[key] = {"shape": entry["shape"],
                                "dtype": entry["dtype"],
                                "shards": list(entry["shards"])}
    return tensors


def _fill_block(block, dst_off, pieces, read):
    """Copy every overlapping saved piece into ``block`` (whose global box
    is ``dst_off``). Returns the number of elements filled."""
    filled = 0
    for piece in pieces:
        src_off = piece["offsets"]
        dst_sl, src_sl = [], []
        empty = False
        for (d0, d1), (s0, s1) in zip(dst_off, src_off):
            lo, hi = max(d0, s0), min(d1, s1)
            if lo >= hi:
                empty = True
                break
            dst_sl.append(slice(lo - d0, hi - d0))
            src_sl.append(slice(lo - s0, hi - s0))
        if empty:
            continue
        src = read(piece)
        block[tuple(dst_sl)] = src[tuple(src_sl)]
        filled += int(np.prod([sl.stop - sl.start for sl in dst_sl]))
    return filled


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0, offload=False):
    """Fill ``state_dict``'s tensors in place from a checkpoint directory,
    resharding each tensor onto its current placement. Reads only the
    pieces this process's devices need."""
    import jax
    import jax.numpy as jnp

    from ..framework.io import ArrayFileReader

    tensors = _merged_metadata(path)
    file_cache: dict[str, ArrayFileReader] = {}

    def read(piece):
        # header-indexed seek+read: only overlapping pieces leave disk
        fname, key = piece["file"], piece["key"]
        if fname not in file_cache:
            file_cache[fname] = ArrayFileReader(os.path.join(path, fname))
        arr = file_cache[fname].read(key)
        want = piece.get("crc32")  # absent in pre-CRC checkpoints
        if want is not None:
            got = _crc32(arr)
            if got != int(want):
                raise CheckpointCorruptionError(
                    f"checkpoint shard {key!r} in "
                    f"{os.path.join(path, fname)} is corrupted: crc32 "
                    f"{got:#010x} != recorded {int(want):#010x}")
        return arr

    missing = []
    for key, target in state_dict.items():
        info = tensors.get(key)
        if info is None:
            missing.append(key)
            continue
        tv = target._value if isinstance(target, Tensor) else None
        if list(info["shape"]) != list(
                tv.shape if tv is not None else np.asarray(
                    state_dict[key]).shape):
            raise ValueError(
                f"{key}: checkpoint shape {info['shape']} != target shape")
        if tv is not None and _is_jax_array(tv) and tv.ndim > 0:
            dtype = tv.dtype
            blocks = []
            for sh in tv.addressable_shards:
                dst_off = _index_to_offsets(sh.index, tv.shape)
                shape = [b - a for a, b in dst_off]
                block = np.empty(shape, dtype=np.dtype(info["dtype"]))
                n = _fill_block(block, dst_off, info["shards"], read)
                if n != int(np.prod(shape)):
                    raise ValueError(
                        f"{key}: shard at {dst_off} only {n}/"
                        f"{int(np.prod(shape))} elements covered by "
                        f"checkpoint pieces")
                blocks.append(jax.device_put(
                    jnp.asarray(block, dtype=dtype), sh.device))
            target._value = jax.make_array_from_single_device_arrays(
                tv.shape, tv.sharding, blocks)
        else:
            # plain array / scalar target: assemble the full value
            full = np.empty(info["shape"], dtype=np.dtype(info["dtype"]))
            dst_off = [[0, s] for s in info["shape"]]
            n = _fill_block(full, dst_off, info["shards"], read)
            if n != int(np.prod(info["shape"], dtype=np.int64)):
                raise ValueError(f"{key}: incomplete checkpoint coverage")
            if isinstance(target, Tensor):
                value = jnp.asarray(full, dtype=target._value.dtype)
                if _is_jax_array(target._value):
                    # keep the target's committed placement (0-d tensors
                    # placed on a mesh must stay there)
                    value = jax.device_put(value, target._value.sharding)
                target._value = value
            else:
                state_dict[key] = full
    if missing:
        raise KeyError(f"checkpoint at {path} is missing keys: {missing}")
    return state_dict


# ---------------------------------------------------------------- snapshots
#
# Step-numbered snapshot directories under one root:
#
#     root/step_00000100/   (per-rank .distcp + .metadata.json, atomic)
#     root/step_00000200/
#
# A snapshot is COMPLETE when every rank recorded by its own
# 0.metadata.json has committed both files — the atomic tmp→rename order
# (shard, then metadata) makes metadata presence the commit marker.
# ``load_latest_snapshot`` walks newest→oldest, skipping incomplete
# directories and (optionally) falling back past corrupted ones.

_SNAP_RE = re.compile(r"^step_(\d+)$")


def _snapshot_dirs(root):
    """[(step, path)] ascending by step."""
    out = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            m = _SNAP_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


def _is_complete(path) -> bool:
    first = os.path.join(path, "0.metadata.json")
    if not os.path.exists(first):
        return False
    try:
        with open(first) as f:
            world = int(json.load(f).get("world_size", 1))
    except (OSError, ValueError):
        return False
    for r in range(world):
        mpath = os.path.join(path, f"{r}.metadata.json")
        if not (os.path.exists(mpath)
                and os.path.exists(os.path.join(path, f"{r}.distcp"))):
            return False
        if r == 0:
            continue
        # every rank must have saved from the SAME world: a directory
        # mixing a 2-rank save with debris from a differently-sized run
        # would pass a bare existence check but merge inconsistent shards
        try:
            with open(mpath) as f:
                if int(json.load(f).get("world_size", 1)) != world:
                    return False
        except (OSError, ValueError):
            return False
    return True


def save_snapshot(state_dict, root, step, keep=None, coordinated=False,
                  commit_timeout=None, gang_layout=False):
    """Save ``state_dict`` under ``root/step_{step:08d}`` (crash-safe,
    checksummed). With ``keep``, the oldest snapshots are pruned so at
    most ``keep`` remain. With ``gang_layout`` (shared-directory gangs,
    see :func:`save_state_dict`) shard naming AND the pruning gate use
    the gang rank — exactly one pruner even when every worker is process
    0 of its own jax runtime, so peers never race to ``rmtree`` the same
    directories. With ``coordinated``, the gang runs a commit barrier
    after the shards land and rank 0 publishes the cluster-agreed
    ``committed_step`` to the gang store — a dead peer surfaces as
    ``PeerFailureError`` from the barrier; an unreachable store leaves
    the step uncommitted (degraded, counted). Returns the snapshot
    directory."""
    import shutil

    import jax

    path = os.path.join(root, f"step_{int(step):08d}")
    save_state_dict(state_dict, path, gang_layout=gang_layout)
    committed = None
    if coordinated and commit_snapshot(root, step, timeout=commit_timeout):
        committed = int(step)
    pruner_rank = _gang_rank() if gang_layout else jax.process_index()
    if keep is not None and pruner_rank == 0:
        if committed is None:
            # pin the published committed step regardless of ``keep`` and
            # of ``coordinated`` — an UNcoordinated emergency save must
            # not prune the one directory the store still points every
            # rank at
            committed = committed_step()
        # only COMPLETE snapshots count toward ``keep`` — an interrupted
        # save must never crowd out the fallback candidates. Incomplete
        # leftovers older than the newest complete snapshot are debris
        # and go too; newer ones may be a concurrent in-flight save. The
        # cluster-agreed committed step is pinned regardless of ``keep``:
        # it is the one directory every rank may still need to resume.
        snaps = _snapshot_dirs(root)
        complete = [(s, p) for s, p in snaps if _is_complete(p)]
        # keep <= 0 keeps nothing (complete[-0:] would keep EVERYTHING)
        keep_set = ({p for _, p in complete[-int(keep):]}
                    if int(keep) > 0 else set())
        if committed is not None:
            keep_set.add(os.path.join(root, f"step_{committed:08d}"))
        newest_step = complete[-1][0] if complete else None
        for s, p in snaps:
            if p in keep_set:
                continue
            if _is_complete(p) or (newest_step is not None
                                   and s < newest_step):
                shutil.rmtree(p, ignore_errors=True)
    return path


# --------------------------------------------- cluster-agreed commit
#
# A snapshot directory being complete on THIS host's disk does not make
# it the gang's resume point: a crash can interrupt a later save after
# some ranks wrote their shards (or, without a shared filesystem, hosts
# can simply disagree on "newest complete"). The commit protocol makes
# the choice cluster-consistent: after every rank's shards land, the
# gang runs a commit barrier and rank 0 publishes ``committed_step`` to
# the supervisor-owned gang store. Loaders with ``coordinated=True``
# resume from exactly that step on every rank; anything newer is
# uncommitted debris that gang rank 0 prunes.


def commit_snapshot(root, step, ctx=None, timeout=None, detector=None,
                    barrier_name=None) -> bool:
    """Commit barrier + publish for ``root/step_{step}``. Returns True
    when the step became the cluster-agreed resume point, False when
    there is no gang or the store was unreachable/partitioned (the step
    stays uncommitted — degraded but safe: loaders fall back to the last
    published step). A dead peer raises ``PeerFailureError``.

    ``barrier_name`` must differ from any EARLIER commit attempt for the
    same step: barrier arrival counts are single-use, so an emergency
    retry reusing the periodic name would see its own stale arrival and
    publish a snapshot the dead peer never finished (fit's emergency
    path passes ``ckpt_emergency/{step}``)."""
    from ..core.resilience import bump_counter
    from . import gang

    ctx = ctx if ctx is not None else gang.gang_context()
    if ctx is None:
        return False
    try:
        gang.gang_barrier(barrier_name or f"ckpt_commit/{int(step)}",
                          ctx=ctx, timeout=timeout, detector=detector)
        if ctx.rank == 0:
            gang.guarded_store_op(
                lambda: ctx.store.set(gang.COMMITTED_STEP_KEY,
                                      str(int(step)).encode()),
                "publish committed_step")
            bump_counter("gang.commit_published")
        bump_counter("gang.commit")
        return True
    except (ConnectionError, TimeoutError, RuntimeError) as e:
        bump_counter("gang.commit_failed")
        logger.warning("commit of snapshot step %s failed (%s); the step "
                       "stays uncommitted", step, e)
        return False


def committed_step(ctx=None):
    """The cluster-agreed snapshot step from the gang store, or None
    (no gang / nothing published yet / store partitioned — callers fall
    back to per-host newest-complete)."""
    from . import gang

    ctx = ctx if ctx is not None else gang.gang_context()
    if ctx is None:
        return None
    try:
        def _read():
            if not ctx.store.check(gang.COMMITTED_STEP_KEY):
                return None
            return int(ctx.store.get(gang.COMMITTED_STEP_KEY).decode())

        return gang.guarded_store_op(_read, "read committed_step")
    except (ConnectionError, TimeoutError, RuntimeError, ValueError) as e:
        logger.warning("cannot read committed step (%s); falling back to "
                       "per-host newest-complete", e)
        return None


def _committed_snapshot_dir(root, ctx=None):
    """(step, path) of the cluster-agreed snapshot when it is resolvable
    AND present/complete on this host, else None (per-host fallback)."""
    step = committed_step(ctx)
    if step is None:
        return None
    path = os.path.join(root, f"step_{int(step):08d}")
    if not _is_complete(path):
        logger.warning(
            "cluster-agreed snapshot step %s is missing or incomplete "
            "under %s on this host; falling back to per-host "
            "newest-complete", step, root)
        return None
    return int(step), path


def latest_complete_snapshot(root, coordinated=False):
    """Newest complete snapshot directory under ``root``, or None. With
    ``coordinated``, the cluster-agreed committed step (when resolvable)
    wins over this host's newest-complete view."""
    if coordinated:
        agreed = _committed_snapshot_dir(root)
        if agreed is not None:
            return agreed[1]
    for _, path in reversed(_snapshot_dirs(root)):
        if _is_complete(path):
            return path
    return None


def load_latest_snapshot(state_dict, root, fallback=True,
                         coordinated=False):
    """Load the newest complete snapshot under ``root`` into
    ``state_dict``. With ``fallback`` (default), a snapshot that fails to
    load — corrupted shard, missing file, coverage gap — is skipped with a
    warning and the next-newest complete one is tried; without it the
    first failure propagates. Returns the directory actually loaded.

    With ``coordinated``, the cluster-agreed ``committed_step`` from the
    gang store picks the directory so every rank resumes at the same
    global step even when a crash interrupted a later partial save; gang
    rank 0 prunes the newer uncommitted debris (exactly one pruner). A
    failure to load the agreed snapshot propagates — silently walking
    back past the agreement would split the gang. When no store is
    reachable (or nothing was ever committed) this degrades to the
    per-host newest-complete walk."""
    if coordinated:
        agreed = _committed_snapshot_dir(root)
        if agreed is not None:
            step, path = agreed
            if _gang_rank() == 0:
                import shutil

                from ..core.resilience import bump_counter

                for s, p in _snapshot_dirs(root):
                    if s > step:
                        logger.warning("pruning uncommitted snapshot "
                                       "debris %s (committed step is %s)",
                                       p, step)
                        bump_counter("gang.debris_pruned")
                        shutil.rmtree(p, ignore_errors=True)
            load_state_dict(state_dict, path)
            return path
    tried = []
    for _, path in reversed(_snapshot_dirs(root)):
        if not _is_complete(path):
            logger.warning("skipping incomplete snapshot %s", path)
            continue
        try:
            load_state_dict(state_dict, path)
            return path
        except (CheckpointCorruptionError, FileNotFoundError, KeyError,
                ValueError) as e:
            if not fallback:
                raise
            logger.warning("snapshot %s failed to load (%s); falling back",
                           path, e)
            tried.append(path)
    raise FileNotFoundError(
        f"no loadable snapshot under {root} "
        f"(failed candidates: {tried or 'none'})")
