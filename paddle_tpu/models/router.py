"""Replica serving fleet: health-gated routing, bit-exact failover,
elastic membership — the tier in front of N ``ServingFrontend`` replicas.

One chip's engine saturates at its slot count; the "millions of users"
architecture is a ROUTER fronting N replicas, built so a replica dying
mid-decode costs one retry, not a lost request:

* **Load-aware dispatch** — each admission is scored against every
  eligible replica's ``health()`` snapshot (queue depth, queued-token
  backlog, in-flight KV slots) and lands on the least-loaded one.
* **Health gating** — a replica is routed around when its router-side
  ``CircuitBreaker`` is open (tripped by failed results or out-of-band
  death evidence), its frontend stopped admitting, or — with a gang
  store — its fleet heartbeat lapsed: a ``PeerFailureDetector``
  (``distributed/gang.py``) sweeping the CURRENT membership marks it
  dead within one ``FLAGS_heartbeat_ttl`` lease.
* **Bit-exact failover** — every engine samples from per-request key
  streams that are a pure function of ``(engine seed, rid, token
  index)``, and the router owns the rid space. A request stranded on a
  failed replica is resubmitted to a healthy one as ``original prompt +
  tokens already emitted`` with ``token_base = len(emitted)`` — the
  continuation is token-identical to the uninterrupted run, whether the
  replay starts from token 0 (replica died, partials unknown) or
  mid-stream (replica retired it ``failed`` with partial output). The
  contract requires every replica to serve the same weights with the
  same engine seed/sampling config (checked at registration, mismatches
  are logged and counted).
* **Hedging** — a tail-latency-sensitive ``submit(hedge=True)`` runs on
  the two best replicas at once; the first terminal result wins and the
  loser is cancelled. Determinism makes the copies token-identical, so
  whichever finishes first is THE answer.
* **Elastic membership** — ``scale_out()`` admits a replica after
  warmup; ``scale_in()`` drains it (``shutdown(drain=True)``: in-flight
  requests finish, queued ones are requeued onto the survivors) before
  deregistering its store presence and heartbeat. Replica processes run
  under the ``launch()`` supervisor with ``restart_policy="worker"``
  (:func:`launch_fleet`): a crashed replica is respawned alone, within
  the supervisor's restart budget, while the survivors keep serving.

The router is a synchronous pump like the frontend: ``submit()`` as
requests arrive, ``step()`` to make progress, ``results(wait=True)`` to
drain. Terminal statuses mirror the frontend's; the retirement switch
(``_RETIREMENT``) is CI-gated to cover every status a replica can emit
(tests/test_no_bare_except.py).

**Durability / hot standby (PR 8).** The router tier itself is no longer
a single point of failure:

* **Write-ahead request journal** (``models/journal.py``, opt-in via
  ``journal=``): every admission is durable before ``submit()`` acks the
  rid, emitted-token progress is checkpointed every K tokens (streamed
  from replica results envelopes), and retirement GC's the record.
  Journal writes batch and flush at step boundaries — bench e4 gates the
  cost < 5% of active processing (``router_journal_overhead_pct``).
* **Leader lease + fencing** (``distributed/gang.py LeaderLease``, via
  ``leader_lease=``): the active router renews a TTL lease whose
  monotonically increasing fencing token rides every envelope to the
  replicas; a ``ServingRouter(standby=True)`` blocks in
  :meth:`take_over` until the lease frees (clean ``shutdown()`` releases
  it — takeover in ~0) or expires (crash — takeover within one lease),
  then replays the journal, re-pins every replica with the new fence
  (the old leader's late writes bounce typed as ``StaleLeaderError`` and
  it stands down instead of double-dispatching), adopts running copies
  whose ``token_base`` sits inside the journaled prefix, and resubmits
  everything else from the last checkpoint — token streams bit-identical
  to the uninterrupted run, by the same per-request key-stream contract
  replica failover rides.
* **Idempotent client surface**: ``submit(rid=...)`` dedups against the
  live request table AND the journal's retired cache, so a client that
  resubmits after a leader change gets the same request (or its cached
  verdict), never a duplicate execution.

**Disaggregated prefill/decode (this PR).** Replicas declare a serving
role (``ServingFrontend(role=...)``: ``prefill`` / ``decode`` /
``both``). When the fleet has both pools, a fresh request runs as two
legs: a one-token prefill on the prefill pool (the engine HOLDS its KV
pages at retirement), then a chunked, CRC-framed, resumable page
transfer (``models/transfer.py``) to a decode replica, which adopts the
pages and produces the rest of the stream — bit-identical to the
colocated run, because the first token is carried over and the decode
leg's key stream continues at index 1 exactly as a colocated second
token would. The failure matrix is typed end to end: source loss at any
point re-prefills on a survivor (``TransferSourceError`` /
``_abandon_transfer``); destination failures charge a bounded transfer
budget (``max_transfer_retries``, exhaustion retires ``failed`` —
never a hang); and a router crash mid-hop is covered by the journal's
HANDOFF record (admit-grade durable BEFORE the decode dispatch acks),
which ``take_over()`` re-drives exactly once via the source's
rid-idempotent export. Roles are ADVISORY: any pool imbalance degrades
requests to colocated serving, never to loss.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import time

import numpy as np

from ..core import perfwatch, telemetry
from ..core.resilience import (
    CircuitBreaker,
    Deadline,
    ServingUnavailable,
    StaleLeaderError,
    TenantQuotaExceeded,
    bump_counter,
    logger,
)
from .frontend import RequestResult, latency_summaries
from .qos import QoSPolicy, tenant_label, tenant_summaries
from .transfer import (
    TransferDestError,
    TransferNoCapacity,
    TransferSourceError,
    transfer_pages,
)

__all__ = ["ServingRouter", "launch_fleet"]

# per-replica membership gauges, exported on every fleet_metrics() call
# so ANY registry snapshot (and every flight dump embedding one) carries
# the fleet view — the data source `obs fleet` renders from a live
# registry, a saved snapshot, or a post-mortem dump alike. Documented in
# README "Observability"; CI-gated against orphaning.
_M_REP_STATE = telemetry.gauge(
    "fleet.replica_state", "per-replica membership state "
    "(1 up / 2 draining / 0 dead)")
_M_REP_BREAKER = telemetry.gauge(
    "fleet.replica_breaker", "router-side breaker state per replica "
    "(0 closed / 1 half-open / 2 open)")
_M_REP_ASSIGNED = telemetry.gauge(
    "fleet.replica_assigned", "requests currently assigned per replica")
_M_REP_SERVED = telemetry.gauge(
    "fleet.replica_served", "requests served per replica")
_M_REP_HB_AGE = telemetry.gauge(
    "fleet.replica_hb_age_s", "age of each replica's last fleet "
    "heartbeat (store-backed fleets only)")
_M_REP_INC = telemetry.gauge(
    "fleet.replica_incarnation", "per-replica incarnation marker: the "
    "{inc=} label carries the replica server's pinned incarnation "
    "prefix (value is always 1)")
_M_REP_ROLE = telemetry.gauge(
    "fleet.replica_role", "per-replica serving role marker: the "
    "{role=} label carries prefill/decode/both (value is always 1)")
_M_XFER_TICKET = telemetry.gauge(
    "fleet.transfer_ticket", "live KV page-transfer tickets, one "
    "labeled point per handoff ({rid=,ticket=,src=}; 1 in flight / "
    "0 resolved)")
_M_XFER_INFLIGHT = telemetry.gauge(
    "fleet.transfer_inflight", "prefill→decode page transfers "
    "currently in flight (awaiting a destination or mid-wire)")

# a call into a replica failing with one of these is REPLICA-level
# evidence (process dead, transport down, server deregistered), not a
# request-level verdict: the router kills the replica and fails over.
# CommTimeoutError is a TimeoutError; InjectedFault a ConnectionError.
_TRANSPORT_ERRORS = (ConnectionError, TimeoutError, ServingUnavailable)


class _Replica:
    """One registered replica: frontend + router-side health state."""

    __slots__ = ("id", "frontend", "breaker", "state", "hb", "assigned",
                 "probes", "served", "h_cache", "h_ts", "p_cache",
                 "role")

    def __init__(self, rep_id, frontend, breaker):
        self.id = rep_id
        self.frontend = frontend
        self.breaker = breaker
        self.state = "up"            # up | draining | dead
        self.role = "both"           # prefill | decode | both (advisory)
        self.hb = None               # store heartbeat handle
        self.assigned: set = set()   # rids currently pending here
        self.probes: set = set()     # rids riding a half-open probe slot
        self.served = 0
        self.h_cache = None          # remote health snapshot + its age
        self.h_ts = 0.0
        self.p_cache = None          # live-progress piggyback (journal)


class _FleetRequest:
    """Router-side record of one client request across failovers."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "priority", "deadline",
                 "emitted", "live", "excluded", "failovers", "hedged",
                 "discard", "deadline_s", "trace", "tenant", "phase",
                 "transfers")

    def __init__(self, rid, prompt, max_new_tokens, priority, deadline,
                 hedged, deadline_s=None, tenant=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.priority = int(priority)
        self.deadline = deadline
        self.deadline_s = deadline_s  # original budget (journal replay)
        self.tenant = tenant          # QoS lane, rides every attempt
        # telemetry trace id minted with the request (router-owned, like
        # the rid): every attempt's spans — across replicas, processes
        # and failover hops — stitch under it. Journal replays mint a
        # fresh one (the trace is observability, not request state).
        self.trace = (telemetry.new_trace_id() if telemetry.enabled()
                      else None)
        self.emitted = np.zeros((0,), np.int32)  # tokens delivered by
        #                                          failed/drained attempts
        self.live: set = set()       # replica ids where rid is pending
        self.excluded: set = set()   # replicas this rid must avoid
        # replicas whose NEXT terminal row for this rid is a takeover
        # artifact (a stale copy the new leader cancelled), not a client
        # verdict — swallowed in _collect, which also re-enables the
        # replica for this rid
        self.discard: set = set()
        self.failovers = 0
        self.hedged = bool(hedged)
        # disaggregated prefill/decode: None = colocated (the default
        # and every fallback), "prefill" = the one-token prefill leg is
        # out, "decode" = prefill retired, the KV handoff / decode leg
        # owns the request. Router-volatile — the journal's HANDOFF
        # record (not this field) is what survives a crash.
        self.phase = None
        self.transfers = 0           # failed transfer attempts (budget)


class ServingRouter:
    """Health-gated, failover-capable router over ``ServingFrontend``
    replicas.

    Usage::

        router = ServingRouter(max_failovers=3)
        router.add_replica(make_frontend())     # N times (or scale_out)
        rid = router.submit(prompt, max_new_tokens=64)
        for rid, res in router.results(wait=True).items():
            print(rid, res.status, res.tokens)

    With a gang ``store``, replicas heartbeat under
    ``{fleet_prefix}/hb`` and a ``PeerFailureDetector`` sweeping the
    current membership routes around a silent death within one lease —
    the same machinery a multi-process fleet under ``launch()`` uses.
    """

    def __init__(self, max_failovers=3, hedge=False,
                 default_max_new_tokens=64, token_unit=64,
                 store=None, fleet_prefix="fleet", lease=None,
                 heartbeat_interval=None, breaker_threshold=3,
                 breaker_cooldown_s=30.0, health_ttl=0.05,
                 journal=None, journal_root=None, leader_lease=None,
                 standby=False, qos=None, max_transfer_retries=3):
        from ..core.flags import flag

        self.max_failovers = int(max_failovers)
        # bounded budget for the prefill→decode page-transfer leg: a
        # destination that keeps failing imports charges this, and
        # exhaustion retires the request "failed" — a handoff can
        # degrade or fail, it can never hang
        self.max_transfer_retries = int(max_transfer_retries)
        self.health_ttl = float(health_ttl)  # remote snapshot reuse window
        self.hedge_default = bool(hedge)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.token_unit = float(token_unit)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        # multi-tenant QoS at the CLIENT surface: quota_tokens bounds a
        # tenant's outstanding fleet-wide cost here (typed
        # TenantQuotaExceeded — the one submit surface that raises);
        # the same policy object is usually shared with the replica
        # frontends, whose WFQ weights it also drives. The default has
        # no quotas: tenant-less traffic is unchanged.
        self.qos = qos if qos is not None else QoSPolicy()
        self._tenant_out: dict = {}   # tenant -> outstanding token cost
        # autoscaler (models/autoscale.py), attached via
        # attach_autoscaler(): its control loop rides step()
        self._autoscaler = None
        self._replicas: dict[int, _Replica] = {}
        self._requests: dict[int, _FleetRequest] = {}
        self._results: dict[int, RequestResult] = {}
        self._parked: list[int] = []
        # live prefill→decode handoffs: rid -> {"ticket", "source"}.
        # An entry exists from export (HANDOFF journaled) until the
        # decode leg dispatches (handoff_done) or the hop is abandoned.
        self._transfers: dict[int, dict] = {}
        self._rids = itertools.count()
        self._rep_ids = itertools.count()
        self._engine_fingerprint = None
        # fleet store (optional): membership keys + replica heartbeats +
        # the lease-based failure detector
        self._store = store
        self._prefix = fleet_prefix
        self._lease = float(lease if lease is not None
                            else flag("FLAGS_heartbeat_ttl"))
        self._hb_interval = float(heartbeat_interval if heartbeat_interval
                                  is not None else max(self._lease / 3, 0.05))
        self._detector = None
        if store is not None:
            from ..distributed.gang import GangContext, PeerFailureDetector

            # publish the beat cadence replica PROCESSES must honor:
            # they beat for themselves (a router-side beat would mask
            # their death), and an interval derived from their own local
            # FLAGS default could exceed this router's lease — replicas
            # would flap dead while perfectly alive (replica_main reads
            # this key before starting its heartbeat). Only the LEADER
            # publishes: a hot standby constructed with a different
            # cadence must not re-pace the live fleet out from under it
            if not standby:
                store.set(f"{fleet_prefix}/hb_interval",
                          repr(self._hb_interval))
            ctx = GangContext(store, rank=-1, world_size=0)
            self._detector = PeerFailureDetector(
                ctx, lease=self._lease, interval=self._hb_interval,
                prefix=f"{fleet_prefix}/hb",
                ranks=self._member_ids).start(beat=False)
        # dispatch-overhead accounting: router bookkeeping vs time inside
        # replica frontends (the acceptance gate records
        # fleet_router_overhead_pct = route_s / wall)
        self._route_s = 0.0
        self._pump_s = 0.0
        # RPC accounting absorbed from remote replicas that left the
        # fleet (scale-in, death, shutdown) so stats() keeps the totals
        self._rpc_retired = {"rpc_s": 0.0, "remote_exec_s": 0.0,
                             "calls": 0}
        self._counts: dict[str, int] = {}
        self._t0 = time.monotonic()
        # fleet-metrics state: last merged snapshot (stats() latency
        # summaries read it) and the previous (tokens_total, ts) pair
        # the fleet tokens/s rate is computed over
        self._last_fleet = None
        self._fm_prev = None
        # fleet-level SLO monitor (perfwatch): evaluates the declared
        # objectives over the MERGED histograms (router + every
        # replica's store-published snapshot), so the burn rate is the
        # fleet's, not one process's — built lazily at first
        # fleet_metrics() call
        self._slo_fleet = None
        # ---- durability / hot standby (see module docstring)
        self._journal = journal
        self._journal_root = journal_root
        self._llease = leader_lease
        self._standby = bool(standby)
        self._deposed = False
        if leader_lease is not None and not standby:
            # the ACTIVE router must hold the lease before serving; a
            # held-by-other lease here is a deployment error (two actives)
            if not leader_lease.wait_acquire(
                    timeout=leader_lease.ttl * 2):
                raise RuntimeError(
                    f"leader lease {leader_lease.key!r} is held by a "
                    "live leader; start this router with standby=True")
        if (self._journal is None and journal_root is not None
                and not standby):
            from .journal import RequestJournal

            # RECOVER, not create: a restart-in-place over an existing
            # journal root must finish what the previous incarnation
            # admitted (the durable-before-ack promise survives the
            # restart) — and must never re-issue a journaled rid
            self._journal = RequestJournal.recover(
                root=journal_root,
                epoch=(leader_lease.fence if leader_lease is not None
                       and leader_lease.fence is not None else 0),
                store=store, prefix=fleet_prefix)
        if self._journal is not None and not standby:
            # adopt whatever live state the journal brought (empty for a
            # fresh root): requests park until replicas register
            n, _, _ = self._restore_requests({})
            if n:
                logger.warning(
                    "journal restart-in-place: %d unfinished request(s) "
                    "recovered; they re-dispatch as replicas register",
                    n)

    # -------------------------------------------------------- membership

    def _member_ids(self):
        return [r.id for r in self._replicas.values() if r.state == "up"]

    def _fingerprint(self, frontend):
        return tuple(frontend.fingerprint())

    def add_replica(self, frontend, replica_id=None, warmup=False):
        """Register a replica (its frontend must already be started) —
        a local ``ServingFrontend`` or a ``RemoteFrontend`` stub for a
        replica process, interchangeably. Returns the replica id. With a
        fleet store, the replica's membership key is published and its
        heartbeat starts (remote replicas beat for THEMSELVES from their
        own process — a router-side beat would mask their death) —
        silent death is then detected by lease, not by a failed
        dispatch. Re-using the id of a DEAD replica replaces the corpse:
        that is how a supervisor-respawned replica process rejoins."""
        rep_id = (next(self._rep_ids) if replica_id is None
                  else int(replica_id))
        while replica_id is None and rep_id in self._replicas:
            rep_id = next(self._rep_ids)
        prev = self._replicas.get(rep_id)
        if prev is not None:
            if prev.state != "dead":
                raise ValueError(f"replica id {rep_id} already registered")
            self._absorb_rpc_stats(prev)
            del self._replicas[rep_id]
        fp = self._fingerprint(frontend)
        if self._engine_fingerprint is None:
            self._engine_fingerprint = fp
        elif fp != self._engine_fingerprint:
            # a mismatched seed/sampling config silently breaks the
            # bit-exact failover contract — loud, counted, but admitted
            # (the operator may be doing a deliberate config rollout)
            bump_counter("fleet.config_mismatch")
            logger.warning(
                "replica %d engine config %r differs from the fleet's %r; "
                "failover replays will NOT be bit-exact", rep_id, fp,
                self._engine_fingerprint)
        if warmup:
            frontend.warmup()
        if (self._llease is not None and self._llease.fence is not None
                and hasattr(frontend, "set_fence")):
            # every envelope to this replica now carries our fencing
            # token; a deposed predecessor's late writes bounce typed
            frontend.set_fence(self._llease.fence)
        if self._journal is not None and hasattr(frontend,
                                                 "want_progress"):
            # journaling routers want the live-progress piggyback on
            # every results envelope (PROGRESS checkpoints ride it)
            frontend.want_progress = True
        rep = _Replica(rep_id, frontend, CircuitBreaker(
            f"fleet.replica.{rep_id}",
            failure_threshold=self.breaker_threshold,
            cooldown_s=self.breaker_cooldown_s))
        # learn the replica's declared serving role (prefill / decode /
        # both) from its health surface. ADVISORY: the candidate filter
        # prefers matching roles but never excludes on it, so a role
        # mismatch degrades to colocated serving, never to loss — and a
        # frontend predating the role field registers as "both".
        with contextlib.suppress(Exception):
            role = (frontend.health() or {}).get("role")
            if role in ("prefill", "decode", "both"):
                rep.role = role
        if self._store is not None:
            self._store.set(f"{self._prefix}/member/{rep_id}", b"up")
            if not getattr(frontend, "is_remote", False):
                rep.hb = self._store.register_heartbeat(
                    rep_id, self._hb_interval, prefix=f"{self._prefix}/hb")
        self._replicas[rep_id] = rep
        bump_counter("fleet.replica_up")
        self._publish_members()
        self._route_parked()
        return rep_id

    def _publish_members(self):
        """Publish the CURRENT membership (with each remote replica's
        RPC address) so a hot standby can rebuild its stubs at takeover
        without configuration. Only the leader writes it."""
        if self._store is None or self._deposed or self._standby:
            return
        members = {}
        for rep in self._replicas.values():
            if rep.state == "dead":
                continue
            fe = rep.frontend
            if getattr(fe, "is_remote", False):
                members[str(rep.id)] = {"worker": fe.worker,
                                        "server": fe.server}
            else:
                members[str(rep.id)] = None  # in-process: not adoptable
        with contextlib.suppress(Exception):
            self._store.set(f"{self._prefix}/members",
                            json.dumps(members).encode())

    def scale_out(self, frontend, replica_id=None, warmup=True):
        """Grow the fleet: warm the replica's compiled shapes FIRST (a
        cold replica would absorb compile time into live requests), then
        admit it and immediately route parked/backlogged work there."""
        bump_counter("fleet.scale_out")
        return self.add_replica(frontend, replica_id=replica_id,
                                warmup=warmup)

    def scale_in(self, replica_id):
        """Shrink the fleet gracefully: stop routing to the replica,
        drain it (in-flight requests FINISH and deliver normally; queued
        ones are requeued onto the survivors with their budgets intact),
        then deregister its membership and heartbeat."""
        rep = self._replicas[replica_id]
        rep.state = "draining"
        bump_counter("fleet.scale_in")
        try:
            rep.frontend.shutdown(drain=True)
        except _TRANSPORT_ERRORS as e:
            # an unreachable replica cannot drain: this scale-in is a
            # death — fail over its stranded requests instead of raising
            # out of the removal with the corpse still registered
            self._kill_replica(rep, f"scale_in drain failed: {e!r}")
        else:
            self._collect(rep)
            self._deregister(rep)
        self._absorb_rpc_stats(rep)
        if telemetry.enabled():
            self._retire_replica_gauges(rep)
        del self._replicas[replica_id]
        self._publish_members()
        self._route_parked()

    @staticmethod
    def _fold_rpc_stats(acc, frontend):
        """Accumulate one remote frontend's transport accounting into
        ``acc`` — the single definition of which keys make up the
        ``fleet_rpc_overhead_pct`` inputs."""
        if getattr(frontend, "is_remote", False):
            with contextlib.suppress(Exception):
                s = frontend.stats()
                acc["rpc_s"] += s.get("rpc_s", 0.0)
                acc["remote_exec_s"] += s.get("remote_exec_s", 0.0)
                acc["calls"] += s.get("calls", 0)

    def _absorb_rpc_stats(self, rep):
        """Keep a departing remote replica's transport accounting in the
        router's running totals (the bench overhead gate reads them
        after the fleet has churned)."""
        self._fold_rpc_stats(self._rpc_retired, rep.frontend)

    def _deregister(self, rep):
        if rep.hb is not None:
            with contextlib.suppress(Exception):
                rep.hb.stop(self._hb_interval + 1)
            rep.hb = None
        if self._store is not None:
            # membership + beat keys must not linger: a deliberate leave
            # is not a death, and the next sweep must not see a stale beat
            with contextlib.suppress(Exception):
                self._store.delete_key(f"{self._prefix}/member/{rep.id}")
            with contextlib.suppress(Exception):
                self._store.delete_heartbeat(rep.id,
                                             prefix=f"{self._prefix}/hb")

    def fail_replica(self, replica_id, reason="operator kill"):
        """Declare a replica dead NOW (fault drills / orchestrator
        signal): trip its breaker, deregister it, and fail over every
        request stranded there."""
        rep = self._replicas.get(replica_id)
        if rep is not None:
            self._kill_replica(rep, reason)

    def _kill_replica(self, rep, reason):
        # ONE death per replica, however many signals report it (lease
        # sweep, transport errors on submit/collect/cancel, operator
        # fail_replica) and however many member PROCESSES back the
        # replica — a TP gang (models/tp_serving.py) registers as one
        # replica id, so a group collapse is one breaker trip, one
        # replica_dead flight event, and one failover charge per
        # stranded rid, not one per member (regression-pinned in
        # tests/test_tp_serving.py)
        if rep.state == "dead":
            return
        rep.state = "dead"
        # the event rides the ring BEFORE the breaker trip dumps it, so
        # the post-mortem file names the dead replica and why
        telemetry.flight_recorder().record(
            "replica_dead", replica=rep.id, reason=str(reason),
            stranded=sorted(rep.assigned))
        rep.breaker.trip()
        bump_counter("fleet.replica_dead")
        logger.warning("replica %d marked dead (%s); failing over %d "
                       "stranded request(s)", rep.id, reason,
                       len(rep.assigned))
        # salvage results the replica already retired before it broke —
        # a terminal verdict that exists must not be recomputed. Short
        # per-call budget: a dead replica PROCESS can't answer, and the
        # salvage must not stall failover for the full rpc timeout.
        with contextlib.suppress(Exception):
            self._collect(rep, timeout=2.0)
        self._deregister(rep)
        self._publish_members()
        for rid in list(rep.assigned):
            rep.assigned.discard(rid)
            freq = self._requests.get(rid)
            if freq is None:
                continue
            freq.live.discard(rep.id)
            freq.excluded.add(rep.id)
            if freq.live:
                continue  # a hedge copy is still running elsewhere
            self._failover(freq, None, f"replica {rep.id} dead: {reason}")
        # the SAME pass sweeps requests mid-handoff: a rid whose page
        # transfer sources from this replica is no longer in
        # rep.assigned (its prefill leg already retired), so the loop
        # above never sees it — without this sweep a ticket in flight
        # would strand its request until the transfer's own wire error
        # surfaced, or forever if no transfer attempt was running
        for rid, xfer in list(self._transfers.items()):
            if xfer["source"] != rep.id:
                continue
            freq = self._requests.get(rid)
            if freq is None:
                self._clear_transfer(rid)
                continue
            self._abandon_transfer(
                freq, f"source replica {rep.id} dead: {reason}")

    # --------------------------------------------------------- dispatch

    def _score(self, h):
        """Load score from one health snapshot — lower is better. The
        three load signals share a scale by normalizing the token
        backlog to ``token_unit`` (≈ one request's decode budget)."""
        return (h["queue_depth"] + h["active_slots"]
                + h["queued_tokens"] / self.token_unit)

    def _accept_health(self, rep, snap):
        """Install a health snapshot unless it is provably STALER than
        the one cached: snapshots are stamped with the sender's
        monotonic clock + incarnation (models/remote.py), so two from
        the same incarnation order by sender time — a delayed results
        envelope's piggyback can no longer out-vote a fresher direct
        probe just by arriving later. Returns the now-current cache."""
        if snap is not None:
            cur = rep.h_cache
            ts, inc = snap.get("_ts"), snap.get("_inc")
            if (cur is not None and ts is not None
                    and inc is not None and cur.get("_inc") == inc
                    and cur.get("_ts") is not None
                    and ts < cur["_ts"]):
                bump_counter("fleet.stale_health_dropped")
            else:
                rep.h_cache, rep.h_ts = snap, time.monotonic()
        return rep.h_cache

    def _disagg_active(self) -> bool:
        """Disaggregated prefill/decode serving is on iff at least one
        up replica declared role=prefill AND at least one up replica
        can decode (role decode/both). Evaluated per admission, so a
        pool that loses its last prefill (or decode) replica degrades
        NEW requests to colocated serving instead of wedging them."""
        has_prefill = has_decode = False
        for rep in self._replicas.values():
            if rep.state != "up":
                continue
            if rep.role == "prefill":
                has_prefill = True
            if rep.role in ("decode", "both"):
                has_decode = True
        return has_prefill and has_decode

    def _candidates(self, freq):
        """Eligible replicas for this request, best (least loaded)
        first. Closed-breaker replicas are preferred; half-open ones are
        used only when no closed one is eligible, and routing there
        consumes the breaker's probe slot (the request IS the probe).

        A disaggregated request's phase steers the pool: the prefill
        leg prefers role prefill/both replicas, everything else (decode
        legs AND colocated requests) prefers decode/both. The steer is
        a sort preference, not a filter — when no matching-role replica
        is eligible the request lands on whatever is, degrading to
        colocated serving rather than starving."""
        want = (("prefill", "both") if freq.phase == "prefill"
                else ("decode", "both"))
        closed, half_open = [], []
        for rep in list(self._replicas.values()):
            if rep.state != "up" or rep.id in freq.excluded:
                continue
            if rep.id in freq.live:
                # a copy of this rid is already pending there (hedge arm
                # or a not-yet-collected attempt) — resubmitting the same
                # rid to that frontend would raise
                continue
            state = rep.breaker.state()
            if state == CircuitBreaker.OPEN:
                continue
            t0 = time.monotonic()
            try:
                # remote probes cost a wire round-trip per call, and the
                # server already answers from a snapshot refreshed at its
                # own pump-turn boundaries — a router-side TTL adds no
                # staleness the wire didn't already imply. Local
                # frontends stay uncached (health() is cheap and tests
                # preload replicas directly between dispatches).
                if (rep.h_cache is not None
                        and getattr(rep.frontend, "is_remote", False)
                        and t0 - rep.h_ts < self.health_ttl):
                    h = rep.h_cache
                else:
                    h = self._accept_health(rep, rep.frontend.health())
                self._pump_s += time.monotonic() - t0
            except StaleLeaderError as e:  # deposed: the replica is
                # fine, WE are not the leader anymore
                self._pump_s += time.monotonic() - t0
                self._stand_down(str(e))
                return []
            except Exception as e:  # a broken health probe is a death
                self._pump_s += time.monotonic() - t0
                self._kill_replica(rep, f"health() raised: {e!r}")
                continue
            if not h["ready"]:
                continue
            (closed if state == CircuitBreaker.CLOSED
             else half_open).append(
                 ((rep.role not in want, self._score(h)), rep.id))
        pool = sorted(closed) or sorted(half_open)
        return pool

    def _submit_to(self, freq, rep_id, kv_import=None):
        rep = self._replicas[rep_id]
        if rep.state != "up":
            # a candidate killed mid-dispatch (transport error on an
            # earlier submit in this same pool walk)
            return False
        probe = rep.breaker.state() == CircuitBreaker.HALF_OPEN
        if probe and not rep.breaker.allow():
            return False
        k = len(freq.emitted)
        if freq.phase == "prefill":
            # the PREFILL leg: full prompt, exactly one token, and the
            # engine holds the request's KV pages for export at retire
            # instead of recycling them
            args = (freq.prompt, 1)
            extra = {"token_base": 0, "hold_kv": True}
        elif kv_import is not None:
            # the DECODE leg of a completed handoff: the full budget
            # from token 0, seeded by the imported pages — the engine
            # adopts them and skips the prefill pass entirely
            args = (freq.prompt, freq.max_new_tokens)
            extra = {"token_base": 0, "kv_import": kv_import}
        else:
            prompt = (np.concatenate([freq.prompt, freq.emitted])
                      if k else freq.prompt)
            args = (prompt, freq.max_new_tokens - k)
            extra = {"token_base": k}
        t0 = time.monotonic()
        try:
            rep.frontend.submit(args[0], args[1],
                                priority=freq.priority,
                                deadline_s=freq.deadline, rid=freq.rid,
                                trace=freq.trace,
                                tenant=freq.tenant, **extra)
            self._pump_s += time.monotonic() - t0
        except StaleLeaderError as e:
            self._pump_s += time.monotonic() - t0
            if probe:
                rep.breaker.release_probe()
            self._stand_down(str(e))
            return False
        except _TRANSPORT_ERRORS as e:
            self._pump_s += time.monotonic() - t0
            # the per-call timeout / resend budget is the router-side
            # evidence a replica PROCESS is gone; the dispatch falls
            # through to the next candidate
            if probe:
                rep.breaker.release_probe()
            self._kill_replica(rep, f"submit transport error: {e!r}")
            return False
        rep.assigned.add(freq.rid)
        freq.live.add(rep_id)
        if probe:
            rep.probes.add(freq.rid)
        if telemetry.enabled():
            # the hop record a stitched timeline reads the request's
            # replica placement (and failover path) off
            telemetry.trace_event("fleet.dispatch", trace=freq.trace,
                                  rid=freq.rid, replica=rep_id,
                                  token_base=extra["token_base"],
                                  phase=freq.phase)
        return True

    def _dispatch(self, freq):
        pool = self._candidates(freq)
        sent = False
        for _, rep_id in pool:
            if self._submit_to(freq, rep_id):
                sent = True
                break
        if sent and freq.hedged:
            for _, rep_id in pool:
                if rep_id not in freq.live and self._submit_to(freq,
                                                               rep_id):
                    bump_counter("fleet.hedged")
                    if telemetry.enabled():
                        telemetry.trace_event("fleet.hedge",
                                              trace=freq.trace,
                                              rid=freq.rid,
                                              replica=rep_id)
                    break
        return sent

    def _failover(self, freq, partial_tokens, reason, charge=True):
        """Resubmit a stranded request. ``partial_tokens`` (if the failed
        attempt surfaced any) extend the emitted prefix so the replay
        resumes mid-stream instead of recomputing; determinism makes the
        continuation bit-identical either way."""
        if partial_tokens is not None and len(partial_tokens):
            freq.emitted = np.concatenate(
                [freq.emitted, np.asarray(partial_tokens, np.int32)])
        if len(freq.emitted) >= freq.max_new_tokens:
            # the failed attempt had in fact finished the budget — the
            # emitted prefix IS the answer
            self._deliver(freq, "ok", freq.emitted, reason)
            return
        if charge:
            freq.failovers += 1
        if freq.failovers > self.max_failovers:
            bump_counter("fleet.failover_budget_exhausted")
            self._deliver(freq, "failed", freq.emitted,
                          f"failover budget exhausted ({reason})")
            return
        bump_counter("fleet.failover")
        if telemetry.enabled():
            telemetry.trace_event("fleet.failover", trace=freq.trace,
                                  rid=freq.rid, reason=str(reason),
                                  emitted=len(freq.emitted))
        telemetry.flight_recorder().record("failover", rid=freq.rid,
                                           reason=str(reason))
        if not self._dispatch(freq):
            if freq.rid not in self._parked:
                self._parked.append(freq.rid)

    def _route_parked(self):
        for rid in list(self._parked):
            freq = self._requests.get(rid)
            if freq is None:
                with contextlib.suppress(ValueError):
                    self._parked.remove(rid)
                continue
            if freq.deadline.expired():
                self._deliver(freq, "timed_out", freq.emitted,
                              "expired while parked at the router")
                continue
            if self._dispatch(freq):
                self._parked.remove(rid)
                continue
            ups = [r for r in self._replicas.values() if r.state == "up"]
            if ups and all(r.id in freq.excluded for r in ups):
                # every live replica already failed this request
                self._deliver(freq, "failed", freq.emitted,
                              "every live replica excluded by failover")

    # ------------------------------------------------------ client API

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_s=None, hedge=None, rid=None,
               tenant=None) -> int:
        """Admit one request to the fleet; returns its rid. The verdict
        lands in ``results()``. ``hedge=True`` (or the router-wide
        default) duplicates the request onto the two least-loaded
        replicas; the first terminal result wins.

        ``tenant`` selects the QoS lane: it rides every attempt to the
        replica frontends (WFQ weight, per-tenant metrics), and the
        router enforces the tenant's fleet-wide ``quota_tokens`` HERE —
        an over-quota admission raises the typed
        :class:`TenantQuotaExceeded` (the one submit surface that
        raises; clients back off on it instead of retrying blind).

        ``rid`` is the IDEMPOTENT client surface: a client that owns its
        request ids can resubmit after a leader change and get the SAME
        request — a rid still pending here (or replayed from the
        journal) acks without duplicating, and a recently retired rid
        re-delivers its journaled verdict instead of re-executing."""
        if rid is not None:
            rid = int(rid)
            if rid in self._requests or rid in self._results:
                bump_counter("fleet.dup_submit")
                return rid
            if self._journal is not None:
                cached = self._journal.retired_result(rid)
                if cached is not None:
                    bump_counter("fleet.dup_submit")
                    status, tokens, reason = cached
                    self._results[rid] = RequestResult(rid, status,
                                                       tokens, reason)
                    return rid
            # keep auto rids strictly above explicit ones (no aliasing)
            self._rids = itertools.count(max(rid + 1, next(self._rids)))
        else:
            rid = next(self._rids)
        prompt = np.asarray(prompt).astype(np.int32).ravel()
        max_new = (self.default_max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        # tenant token-budget quota, BEFORE the journal sees the admit:
        # an over-quota request must not become durable state the
        # standby would replay
        cost = int(prompt.size) + max_new
        held = self._tenant_out.get(tenant, 0)
        if not self.qos.check_quota(tenant, held, cost):
            bump_counter("serving.quota_rejected")
            if telemetry.enabled():
                telemetry.counter("serving.quota_rejected").inc(
                    tenant=tenant_label(tenant))
            raise TenantQuotaExceeded(
                f"tenant {tenant_label(tenant)} over quota: {held} "
                f"outstanding + {cost} > "
                f"{self.qos.quota_tokens(tenant)} tokens",
                tenant=tenant)
        # leadership is re-checked at ADMISSION, not just in step(): a
        # leader whose lease lapsed mid-partition (renewal thread stood
        # down, no step() since) must not ack an ADMIT into a journal
        # epoch the new leader has already recovered past — an acked rid
        # nobody will ever serve. held() is an in-memory flag; this
        # costs no store round-trip.
        self._check_leadership()
        if self._standby or self._deposed:
            # not the leader: admitting here would double-serve against
            # the journal's owner — the client must talk to the leader
            bump_counter("fleet.not_leader_rejected")
            self._results[rid] = RequestResult(
                rid, "unavailable", None,
                "this router is not the fleet leader")
            return rid
        deadline = (deadline_s if isinstance(deadline_s, Deadline)
                    else Deadline(deadline_s))
        freq = _FleetRequest(rid, prompt, max_new, priority, deadline,
                             self.hedge_default if hedge is None else hedge,
                             deadline_s=(None if isinstance(deadline_s,
                                                            Deadline)
                                         else deadline_s),
                             tenant=tenant)
        self._requests[rid] = freq
        self._tenant_out[tenant] = held + cost
        if (not freq.hedged and max_new > 1 and self._disagg_active()):
            # disaggregated flow: the first leg is a one-token prefill
            # on the prefill pool; the KV pages hand off to a decode
            # replica at its retirement. Hedged requests stay colocated
            # (two prefill arms would race one another's handoff), as
            # do single-token requests (there is nothing to decode).
            freq.phase = "prefill"
        t0 = time.monotonic()
        pump0 = self._pump_s  # frontend.submit time lands in pump_s
        if self._journal is not None:
            # durable BEFORE the rid is acked: a router crash after this
            # point can lose the process, not the request
            self._journal.admit(rid, prompt, max_new,
                                priority=freq.priority,
                                deadline_s=freq.deadline_s,
                                hedge=freq.hedged, tenant=freq.tenant)
            self._journal.flush()
        if not self._dispatch(freq):
            self._parked.append(rid)
            bump_counter("fleet.parked")
        self._route_s += ((time.monotonic() - t0)
                          - (self._pump_s - pump0))
        return rid

    def cancel(self, rid) -> bool:
        """Cancel a request wherever it lives (parked or on replicas).
        Partial tokens an in-flight copy already produced are preserved
        in the delivered result (same contract as
        ``ServingFrontend.cancel``)."""
        freq = self._requests.get(rid)
        if freq is None:
            return False
        for rep_id in list(freq.live):
            rep = self._replicas.get(rep_id)
            if rep is None or rep.state != "up":
                continue
            # frontend.cancel records a "cancelled" result carrying the
            # partial tokens; collecting it routes through the normal
            # retirement switch, which delivers emitted + partials
            try:
                rep.frontend.cancel(rid)
            except StaleLeaderError as e:
                self._stand_down(str(e))
                return False  # the new leader owns the request now
            except _TRANSPORT_ERRORS as e:
                self._kill_replica(rep, f"cancel transport error: {e!r}")
                if rid not in self._requests:
                    return True  # the kill's failover resolved it
                continue
            except Exception:  # noqa: BLE001 — replica-local refusal
                bump_counter("fleet.cancel_error")
            self._collect(rep)
            if rid not in self._requests:
                return True
        self._deliver(freq, "cancelled", freq.emitted,
                      "cancelled by caller")
        return True

    def pending(self) -> int:
        return len(self._requests)

    def step(self):
        """One fleet turn: sweep liveness (lease-based death detection),
        route parked work, pump every live replica one scheduler turn,
        run the retirement switch over everything that finished, and
        land the journal's batched records."""
        if not self._check_leadership():
            return
        if self._autoscaler is not None:
            # OUTSIDE the route_s window: the autoscaler's decision loop
            # has its own overhead accounting (autoscale_overhead_pct,
            # gated < 3% in bench e7), and a scale-out's warmup is
            # useful work, not routing overhead
            self._autoscaler.maybe_step()
        t_start = time.monotonic()
        pump0 = self._pump_s  # every frontend call below adds to pump_s
        self._sweep_liveness()
        self._route_parked()
        self._pump_transfers()
        for rep in list(self._replicas.values()):
            if rep.state != "up":
                continue
            t0 = time.monotonic()
            try:
                if not getattr(rep.frontend, "is_remote", False):
                    # remote replicas pump THEMSELVES (ReplicaServer's
                    # pump thread); the router's turn is just the
                    # results fetch below
                    if (rep.frontend.pending()
                            or rep.frontend.engine.has_work()):
                        rep.frontend.step()
            except Exception as e:  # replica broke mid-dispatch
                self._pump_s += time.monotonic() - t0
                self._kill_replica(rep, f"step() raised: {e!r}")
                continue
            self._pump_s += time.monotonic() - t0
            self._collect(rep)
            if self._deposed:
                return  # a fenced rejection mid-turn: stop immediately
        self._route_parked()
        self._journal_progress()
        self._route_s += ((time.monotonic() - t_start)
                          - (self._pump_s - pump0))

    def _check_leadership(self) -> bool:
        """False once this router is deposed (its lease lapsed, was
        superseded, or a replica fenced it off) — it stops dispatching;
        the new leader owns every pending request via the journal."""
        if (not self._deposed and self._llease is not None
                and not self._standby and not self._llease.held()):
            self._stand_down("leader lease lost (expired or superseded)")
        return not self._deposed

    def _stand_down(self, reason):
        if self._deposed:
            return
        self._deposed = True
        bump_counter("fleet.deposed")
        logger.warning(
            "router standing down (%s); %d pending request(s) belong to "
            "the new leader via the journal", reason,
            len(self._requests))
        # a deposed leader is a post-mortem moment (StaleLeaderError
        # fencing rejection or a lapsed lease): leave the artifact
        telemetry.flight_dump("stand_down", detail=str(reason),
                              pending=len(self._requests))
        if self._llease is not None:
            self._llease.stand_down()
        if self._journal is not None:
            # a later re-promotion (take_over) recovers from disk under
            # a fresh fence; keep the root, drop the closed handle
            self._journal_root = self._journal.root
            with contextlib.suppress(Exception):
                self._journal.flush()
                self._journal.close()
            self._journal = None

    def _journal_progress(self):
        """Checkpoint emitted-token progress (journal PROGRESS records,
        every K tokens per rid) from the freshest per-replica progress
        view — streamed piggyback for remote replicas, a direct
        ``progress()`` call for local ones — then flush the step's
        batched records."""
        if self._journal is None:
            return
        for rep in self._replicas.values():
            if rep.state != "up":
                continue
            if getattr(rep.frontend, "is_remote", False):
                prog, rep.p_cache = rep.p_cache, None
            else:
                try:
                    prog = rep.frontend.progress()
                except Exception:  # noqa: BLE001 — progress is an
                    # optimization; the admit record alone stays correct
                    bump_counter("fleet.progress_error")
                    continue
            if not prog:
                continue
            for rid, (base, toks) in prog.items():
                freq = self._requests.get(rid)
                if freq is None or not len(toks):
                    continue
                if base > len(freq.emitted) or rid not in rep.assigned:
                    continue  # resumed past a lost checkpoint / stale
                # anchor at the attempt's stream offset: an ADOPTED
                # takeover copy runs with base BELOW the journaled
                # prefix (concat would duplicate); the known prefix up
                # to base + the attempt's tokens is the true stream,
                # journaled only when it actually grows
                merged = (np.concatenate([freq.emitted[:base], toks])
                          if base else toks)
                self._journal.progress(rid, merged)
        self._journal.flush()

    def results(self, wait=False, timeout_s=None) -> dict:
        """Pop terminal results as ``{rid: RequestResult}``. With
        ``wait=True`` the router pumps until every pending request
        resolves, the fleet has no live replica left (remaining requests
        deliver ``unavailable``), or ``timeout_s`` expires (remaining
        deliver ``timed_out``)."""
        if wait:
            deadline = Deadline(timeout_s)
            while self._requests:
                if self._deposed:
                    # the new leader owns the pending requests (journal);
                    # deliver only what already resolved here
                    break
                if not any(r.state == "up"
                           for r in self._replicas.values()):
                    for freq in list(self._requests.values()):
                        self._deliver(freq, "unavailable", freq.emitted,
                                      "no live replica")
                    break
                if deadline.expired():
                    for freq in list(self._requests.values()):
                        self._deliver(freq, "timed_out", freq.emitted,
                                      "results(wait) timeout")
                    break
                self.step()
        out, self._results = self._results, {}
        return out

    # ------------------------------------------------------- retirement

    # status -> handler; CI-gated (tests/test_no_bare_except.py) to cover
    # every terminal state a frontend result can carry, so a new engine
    # status cannot silently fall through the switch
    _RETIREMENT = {
        "ok": "_retire_ok",
        "failed": "_retire_failed",
        "timed_out": "_retire_timed_out",
        "cancelled": "_retire_cancelled",
        "rejected": "_retire_rejected",
        "unavailable": "_retire_unavailable",
    }

    def _collect(self, rep, timeout=None):
        t0 = time.monotonic()
        try:
            fetched = rep.frontend.results(timeout=timeout)
        except StaleLeaderError as e:
            self._pump_s += time.monotonic() - t0
            self._stand_down(str(e))
            return
        except _TRANSPORT_ERRORS as e:
            self._pump_s += time.monotonic() - t0
            self._kill_replica(rep, f"results transport error: {e!r}")
            return
        self._pump_s += time.monotonic() - t0
        # a remote results envelope carries the replica's health snapshot
        # (and live progress, for the journal) for free — refresh the
        # caches without spending separate wire round-trips
        self._accept_health(rep,
                            getattr(rep.frontend, "piggyback_health",
                                    None))
        prog = getattr(rep.frontend, "piggyback_progress", None)
        if prog is not None:
            rep.p_cache = prog
        for rid, res in fetched.items():
            rep.assigned.discard(rid)
            rep.probes.discard(rid)
            freq = self._requests.get(rid)
            if freq is None:
                continue  # already delivered (hedge loser, late cancel)
            freq.live.discard(rep.id)
            if rep.id in freq.discard:
                # a takeover artifact: the new leader cancelled this
                # stale copy (its token_base outran the journaled
                # prefix); the row is not a client verdict. The replica
                # is re-eligible for the rid once the row is consumed.
                freq.discard.discard(rep.id)
                freq.excluded.discard(rep.id)
                if (not freq.live and rid in self._requests
                        and rid not in self._parked):
                    self._failover(freq, None,
                                   "stale takeover copy discarded",
                                   charge=False)
                continue
            handler = self._RETIREMENT.get(res.status)
            if handler is None:
                # unreachable when the CI guard holds; deliver verbatim
                # rather than dropping the request on the floor
                bump_counter("fleet.unknown_terminal")
                self._deliver(freq, res.status, res.tokens, res.reason)
                continue
            getattr(self, handler)(rep, freq, res)

    def _note_verdict(self, rep, rid, ok):
        if ok:
            rep.breaker.record_success()
        else:
            rep.breaker.record_failure()
        rep.probes.discard(rid)

    def _combine(self, freq, res):
        """Full token stream for a terminal attempt: the known emitted
        prefix up to the attempt's ``token_base`` + the attempt's own
        tokens. ``None`` when the attempt resumed PAST the known prefix
        (a journaled checkpoint was lost): the gap tokens are
        unrecoverable from this result, so the caller must replay from
        the prefix instead — determinism regenerates them exactly."""
        base = int(getattr(res, "token_base", 0) or 0)
        if base > len(freq.emitted):
            bump_counter("fleet.progress_gap")
            return None
        if base == 0:
            return res.tokens
        return np.concatenate([freq.emitted[:base], res.tokens])

    def _retire_ok(self, rep, freq, res):
        self._note_verdict(rep, freq.rid, ok=True)
        rep.served += 1
        if freq.phase == "prefill":
            # not a client verdict: the one-token prefill leg finished
            # and the replica is holding its KV pages — begin the hop
            self._begin_handoff(rep, freq, res)
            return
        tokens = self._combine(freq, res)
        if tokens is None:
            self._failover(freq, None,
                           f"replica {rep.id} finished past the known "
                           "prefix (lost checkpoint); replaying",
                           charge=False)
            return
        self._deliver(freq, "ok", tokens, res.reason)

    def _extend_emitted(self, freq, res):
        """Grow the known emitted prefix with an attempt's partial
        tokens, anchored at the attempt's ``token_base`` (partials past
        a lost checkpoint are ignored — determinism regenerates them)."""
        base = int(getattr(res, "token_base", 0) or 0)
        if base > len(freq.emitted) or not len(res.tokens):
            return
        merged = (np.concatenate([freq.emitted[:base], res.tokens])
                  if base else np.asarray(res.tokens, np.int32))
        if len(merged) > len(freq.emitted):
            freq.emitted = merged

    def _retire_failed(self, rep, freq, res):
        self._note_verdict(rep, freq.rid, ok=False)
        # exclude UNCONDITIONALLY: even when a hedge copy survives, a
        # later failover must not land back on the replica that already
        # failed this exact rid
        freq.excluded.add(rep.id)
        if freq.live:
            bump_counter("fleet.hedge_arm_failed")
            return  # the surviving hedge copy is the failover
        self._extend_emitted(freq, res)
        self._failover(freq, None,
                       f"replica {rep.id} failed it: {res.reason}")

    def _retire_timed_out(self, rep, freq, res):
        # the deadline is the CLIENT's budget: replaying elsewhere cannot
        # win back wall time that is already spent
        tokens = self._combine(freq, res)
        self._deliver(freq, "timed_out",
                      freq.emitted if tokens is None else tokens,
                      res.reason)

    def _retire_cancelled(self, rep, freq, res):
        if rep.state != "up":
            # a draining/dead replica handing the request back is not a
            # client cancel: requeue it (budget intact — no charge). A
            # surviving hedge copy IS the requeue — drop this arm.
            if freq.live:
                bump_counter("fleet.hedge_arm_dropped")
                return
            self._extend_emitted(freq, res)
            self._failover(freq, None,
                           f"replica {rep.id} drained", charge=False)
            return
        tokens = self._combine(freq, res)
        self._deliver(freq, "cancelled",
                      freq.emitted if tokens is None else tokens,
                      res.reason)

    def _retire_rejected(self, rep, freq, res):
        # the replica's admission control shed it; another replica may
        # have room (malformed requests reject everywhere and exhaust
        # the budget quickly)
        freq.excluded.add(rep.id)
        if freq.live:
            return
        self._failover(freq, None,
                       f"replica {rep.id} rejected it: {res.reason}")

    def _retire_unavailable(self, rep, freq, res):
        # the replica's own breaker refused it — evidence for the
        # router's breaker too, then reroute
        self._note_verdict(rep, freq.rid, ok=False)
        freq.excluded.add(rep.id)
        if freq.live:
            return
        self._failover(freq, None, f"replica {rep.id} unavailable")

    def _deliver(self, freq, status, tokens=None, reason=None):
        self._results[freq.rid] = RequestResult(
            freq.rid, status, tokens, reason)
        self._counts[status] = self._counts.get(status, 0) + 1
        if self._requests.pop(freq.rid, None) is not None:
            # release the tenant's outstanding quota hold (the single
            # terminal point every delivery path funnels through)
            left = (self._tenant_out.get(freq.tenant, 0)
                    - (int(freq.prompt.size) + freq.max_new_tokens))
            if left > 0:
                self._tenant_out[freq.tenant] = left
            else:
                self._tenant_out.pop(freq.tenant, None)
        if self._journal is not None:
            # terminal verdict journaled: GCs the live record and backs
            # the exactly-once resubmit cache (flushed at step/submit
            # boundaries — a crash in between replays the request, and
            # determinism re-derives the same verdict)
            self._journal.retire(freq.rid, status, tokens, reason)
        with contextlib.suppress(ValueError):
            self._parked.remove(freq.rid)
        if freq.rid in self._transfers:
            # delivered mid-hop (cancel, timeout, exhausted budget):
            # free the source's export pin and the ticket gauge
            self._release_export(self._transfers[freq.rid])
            self._clear_transfer(freq.rid)
        for rep_id in list(freq.live):
            rep = self._replicas.get(rep_id)
            if rep is None:
                continue
            rep.assigned.discard(freq.rid)
            if freq.rid in rep.probes:
                # this copy resolves with no verdict on the replica:
                # free the half-open probe slot it was riding
                rep.probes.discard(freq.rid)
                rep.breaker.release_probe()
            if rep.state == "up":
                try:
                    rep.frontend.cancel(freq.rid)
                except StaleLeaderError as e:
                    self._stand_down(str(e))
                except _TRANSPORT_ERRORS as e:
                    # a cancel that cannot reach the replica is replica
                    # death evidence like any other call — swallowing it
                    # would leave the corpse "up" to stall every future
                    # hedged delivery for the full rpc budget
                    self._kill_replica(rep,
                                       f"cancel transport error: {e!r}")
                except Exception:  # noqa: BLE001 — a failed cancel on a
                    # live replica only means the copy runs to completion
                    bump_counter("fleet.cancel_error")
        freq.live.clear()

    # --------------------------------------- prefill→decode handoff

    def _begin_handoff(self, rep, freq, res):
        """A prefill leg retired ``ok`` on ``rep``: export its KV hold
        as a transfer ticket, journal the hop (HANDOFF is admit-grade
        durable BEFORE any decode dispatch can ack), then drive the
        page transfer. Every failure here degrades to a colocated
        replay — the known first token keeps the replayed stream
        bit-identical."""
        tokens = self._combine(freq, res)
        if tokens is None or not len(tokens):
            freq.phase = None
            bump_counter("fleet.handoff_no_hold")
            self._failover(freq, None,
                           f"prefill on replica {rep.id} surfaced no "
                           "token; replaying colocated", charge=False)
            return
        try:
            ticket = rep.frontend.export_pages(freq.rid)
        except StaleLeaderError as e:
            self._stand_down(str(e))
            return
        except _TRANSPORT_ERRORS as e:
            # the source died between retiring the prefill and the
            # export: its pages died with it — plain failover
            self._kill_replica(rep, f"export transport error: {e!r}")
            if freq.rid in self._requests:
                freq.phase = None
                self._failover(
                    freq, None,
                    f"prefill source {rep.id} died before export")
            return
        if ticket is None:
            # the engine holds no pages for the rid (evicted, or the
            # prefill surfaced no first token): colocated replay
            freq.phase = None
            bump_counter("fleet.handoff_no_hold")
            self._failover(freq, None,
                           f"replica {rep.id} has no KV hold for the "
                           "handoff; replaying colocated", charge=False)
            return
        freq.phase = "decode"
        freq.emitted = np.asarray(tokens, np.int32)
        if self._journal is not None:
            # durable BEFORE the decode dispatch acks: a router crash
            # anywhere in the hop leaves a record take_over() re-drives
            # exactly once (handoff_done, or the retire, erases it)
            self._journal.handoff(freq.rid, source=rep.id,
                                  ticket=ticket["ticket"],
                                  first_token=int(freq.emitted[0]),
                                  prefill_len=int(freq.prompt.size))
            self._journal.flush()
        self._transfers[freq.rid] = {"ticket": ticket, "source": rep.id}
        bump_counter("fleet.transfer_started")
        if telemetry.enabled():
            _M_XFER_TICKET.set(1, rid=str(freq.rid),
                               ticket=str(ticket["ticket"])[:8],
                               src=str(rep.id))
            _M_XFER_INFLIGHT.set(len(self._transfers))
            telemetry.trace_event("fleet.handoff", trace=freq.trace,
                                  rid=freq.rid, source=rep.id,
                                  ticket=ticket["ticket"],
                                  pages=ticket["n_pages"])
        self._advance_handoff(freq)

    def _advance_handoff(self, freq):
        """Drive one live handoff forward: pick a decode destination,
        run the chunked CRC-framed transfer (``models/transfer.py``),
        dispatch the decode leg. No eligible destination parks the hop
        (``_pump_transfers`` retries it every step); destination
        failures charge the bounded transfer budget; source loss
        abandons the hop and re-prefills."""
        xfer = self._transfers.get(freq.rid)
        if xfer is None:
            return
        src = self._replicas.get(xfer["source"])
        if src is None or src.state != "up":
            self._abandon_transfer(
                freq, f"source replica {xfer['source']} died before "
                "the transfer")
            return
        ticket = xfer["ticket"]
        # phase=="decode" steers _candidates to the decode pool; the
        # SOURCE is excluded explicitly — its pages are already there,
        # and importing onto it would collide with its own export hold
        pool = [c for c in self._candidates(freq) if c[1] != src.id]
        if freq.rid not in self._requests:
            return  # a kill inside _candidates resolved the request
        if not pool:
            # no eligible destination AT ALL (breakers open, decode
            # pool dead): charge the transfer budget so the hop cannot
            # wait forever — on exhaustion degrade to a colocated
            # re-prefill (zero loss; the source's prefix cache makes
            # the replay cheap). TRANSIENT gaps (a cooldown expiring,
            # a scale-out landing) resume on an earlier retry.
            freq.transfers += 1
            if freq.transfers > self.max_transfer_retries:
                self._abandon_transfer(
                    freq, "no eligible decode destination")
            return
        dest = None
        for _, dest_id in pool:
            cand = self._replicas[dest_id]
            t0 = time.monotonic()
            try:
                transfer_pages(src.frontend, cand.frontend, ticket,
                               max_chunk_retries=self.max_transfer_retries)
                self._pump_s += time.monotonic() - t0
                dest = cand
                break
            except TransferNoCapacity:
                self._pump_s += time.monotonic() - t0
                # backpressure, not breakage: the pool is full NOW, the
                # same wait a colocated request queues through — try the
                # next destination, else retry the hop next step
                bump_counter("fleet.transfer_backpressure")
                continue
            except TransferSourceError as e:
                self._pump_s += time.monotonic() - t0
                self._abandon_transfer(freq, str(e))
                return
            except TransferDestError as e:
                self._pump_s += time.monotonic() - t0
                bump_counter("fleet.transfer_failed")
                # breaker evidence against the destination (a dead one
                # is ALSO killed by its next direct probe/collect), and
                # one charge against the bounded transfer budget
                self._note_verdict(cand, freq.rid, ok=False)
                freq.transfers += 1
                if freq.transfers > self.max_transfer_retries:
                    bump_counter("fleet.transfer_budget_exhausted")
                    self._deliver(freq, "failed", freq.emitted,
                                  f"transfer budget exhausted: {e}")
                return
        if dest is None:
            return  # every destination full; retried by _pump_transfers
        if not self._submit_to(freq, dest.id,
                               kv_import=ticket["ticket"]):
            # the destination died between landing the import and the
            # dispatch — the landed pages died with it; charge + retry
            bump_counter("fleet.transfer_failed")
            freq.transfers += 1
            if (freq.transfers > self.max_transfer_retries
                    and freq.rid in self._requests):
                bump_counter("fleet.transfer_budget_exhausted")
                self._deliver(freq, "failed", freq.emitted,
                              "transfer budget exhausted: decode "
                              "dispatch failed")
            return
        bump_counter("fleet.transfer_completed")
        if self._journal is not None:
            # the decode replica owns the request now: clear the hop so
            # a takeover does NOT re-drive it (PROGRESS/RETIRE records
            # cover recovery from here on)
            self._journal.handoff_done(freq.rid)
            self._journal.flush()
        self._release_export(xfer)
        self._clear_transfer(freq.rid)

    def _pump_transfers(self):
        """Retry handoffs that could not complete when they began (no
        eligible destination yet, a destination that failed) — called
        once per step so a parked hop resumes the moment the pool
        allows, and a hopeless one times out instead of hanging."""
        for rid in list(self._transfers):
            freq = self._requests.get(rid)
            if freq is None:
                # delivered out from under the hop (cancel/timeout
                # race): free the pin + gauge
                xfer = self._transfers.get(rid)
                if xfer is not None:
                    self._release_export(xfer)
                self._clear_transfer(rid)
                continue
            if freq.live:
                continue  # the decode leg is already out
            if freq.deadline.expired():
                self._deliver(freq, "timed_out", freq.emitted,
                              "expired awaiting the decode handoff")
                continue
            self._advance_handoff(freq)

    def _abandon_transfer(self, freq, reason):
        """The hop's pages are gone (source death, respawned source,
        lost/released ticket): drop it and replay the request from the
        known prefix — the prefill's first token is already in
        ``emitted``, so the replay resubmits ``prompt + [first]`` with
        ``token_base=1`` and the stream stays bit-identical."""
        xfer = self._transfers.get(freq.rid)
        if xfer is not None:
            # a LIVE source still pins the exported pages (e.g. the hop
            # was abandoned for want of a destination, not for source
            # death): free them BEFORE the replay — the re-prefill's
            # admission may need those very pages. No-op on a dead one.
            self._release_export(xfer)
        self._clear_transfer(freq.rid)
        bump_counter("fleet.transfer_abandoned")
        if self._journal is not None:
            # keep the first token durable past the record we clear
            self._journal.progress(freq.rid, freq.emitted)
            self._journal.handoff_done(freq.rid)
            self._journal.flush()
        freq.phase = None
        self._failover(freq, None, f"transfer abandoned: {reason}")

    def _release_export(self, xfer):
        """Best-effort release of the source's export pin (idempotent
        server-side). A failure is counted, not raised: a dead source's
        pages died with it, and a live one frees them at its next
        engine restart at the latest."""
        src = self._replicas.get(xfer["source"])
        if src is None or src.state != "up":
            return
        try:
            src.frontend.release_export(xfer["ticket"]["ticket"])
        except StaleLeaderError as e:
            self._stand_down(str(e))
        except Exception:  # noqa: BLE001 — best-effort cleanup; the
            # source's own death handling reclaims the pages
            bump_counter("fleet.release_export_failed")

    def _clear_transfer(self, rid):
        xfer = self._transfers.pop(rid, None)
        if xfer is None or not telemetry.enabled():
            return
        _M_XFER_TICKET.set(0, rid=str(rid),
                           ticket=str(xfer["ticket"]["ticket"])[:8],
                           src=str(xfer["source"]))
        _M_XFER_INFLIGHT.set(len(self._transfers))

    # --------------------------------------------------- liveness sweep

    def _sweep_liveness(self):
        if self._detector is None:
            return
        for rep_id in self._detector.dead_peers():
            rep = self._replicas.get(rep_id)
            if rep is not None and rep.state == "up":
                self._kill_replica(
                    rep, f"heartbeat lease ({self._lease:g}s) expired")

    # ------------------------------------------------------- takeover

    def _adopt_members(self):
        """Rebuild replica stubs from the membership registry the old
        leader published (remote replicas only — an in-process frontend
        cannot be re-addressed; tests hand those over via
        ``add_replica`` before takeover)."""
        if self._store is None:
            return
        key = f"{self._prefix}/members"
        if not self._store.check(key):
            return
        try:
            members = json.loads(self._store.get_now(key).decode())
        except (ValueError, KeyError, RuntimeError, ConnectionError,
                TimeoutError):
            bump_counter("fleet.members_unreadable")
            return
        from .remote import RemoteFrontend

        for rep_id, info in members.items():
            rep_id = int(rep_id)
            if info is None or rep_id in self._replicas:
                continue
            try:
                self.add_replica(RemoteFrontend(info["worker"],
                                                server=info["server"]),
                                 replica_id=rep_id)
            except Exception as e:  # noqa: BLE001 — a dead member must
                # not sink the takeover; its requests replay elsewhere
                bump_counter("fleet.member_adopt_failed")
                logger.warning("takeover: could not adopt replica %d "
                               "(%s)", rep_id, e)

    def take_over(self, timeout=None) -> dict:
        """Hot-standby promotion: block until the leader lease frees
        (clean release → ~0; crash → within one ttl), then replay the
        journal and resume serving exactly where the dead leader
        stopped:

        1. acquire the lease — the fencing token this takeover runs
           under is now the highest in the fleet;
        2. recover the journal (store index or ``journal_root``) into a
           fresh epoch file;
        3. rebuild replica stubs from the membership registry and
           **re-pin** every replica: the fence handshake makes the old
           leader's late writes bounce typed, and returns each
           replica's live request state;
        4. ADOPT running copies whose ``token_base`` sits inside the
           journaled prefix (their eventual results recombine exactly);
           cancel-and-replay copies that outran a lost checkpoint; and
           resubmit everything not live anywhere from its last
           checkpoint — all bit-identical to the uninterrupted run by
           the per-request key-stream contract.

        Returns a summary dict (requests/adopted/resubmitted/fence)."""
        if self._llease is None:
            raise ValueError("take_over() needs a leader_lease")
        if not self._llease.wait_acquire(timeout=timeout):
            raise TimeoutError(
                f"leader lease {self._llease.key!r} not acquired within "
                f"{timeout}s (holder still renewing)")
        fence = self._llease.fence
        self._standby = False
        self._deposed = False
        try:
            return self._promote(fence)
        except BaseException:
            # a FAILED promotion (journal unreadable, outranked by a
            # concurrent higher-fence takeover, ...) must not leave a
            # half-promoted leader that accepts submissions with no
            # replayed journal: restore standby state, drop the lease
            # hold, and let the caller retry take_over()
            self._standby = True
            if self._journal is not None:
                self._journal_root = self._journal.root
                with contextlib.suppress(Exception):
                    self._journal.close()
                self._journal = None
            with contextlib.suppress(Exception):
                self._llease.stand_down()
            raise

    def _promote(self, fence) -> dict:
        """The body of :meth:`take_over`, after the lease is held —
        split out so a failure anywhere rolls the router back to
        standby (see take_over's except)."""
        if self._journal is None:
            from .journal import RequestJournal

            self._journal = RequestJournal.recover(
                root=self._journal_root, epoch=fence, store=self._store,
                prefix=self._prefix)
        if self._store is not None:
            # the fleet now paces to THIS router's cadence (deferred
            # from __init__: a standby must not re-pace a live leader)
            with contextlib.suppress(Exception):
                self._store.set(f"{self._prefix}/hb_interval",
                                repr(self._hb_interval))
        self._adopt_members()
        self._publish_members()
        # re-pin: push the new fence + learn each replica's live state
        live_map: dict[int, list] = {}
        for rep in list(self._replicas.values()):
            if rep.state != "up":
                continue
            if hasattr(rep.frontend, "want_progress"):
                # replicas handed over pre-promotion (before the journal
                # existed) must start shipping the progress piggyback
                rep.frontend.want_progress = True
            t0 = time.monotonic()
            try:
                if getattr(rep.frontend, "is_remote", False):
                    info = rep.frontend.repin(fence)
                else:
                    info = rep.frontend.progress()
                self._pump_s += time.monotonic() - t0
            except StaleLeaderError:
                # a replica already serves a HIGHER fence: a concurrent
                # takeover outranks this one — abort the promotion (the
                # except in take_over rolls us back to standby)
                self._pump_s += time.monotonic() - t0
                raise
            except _TRANSPORT_ERRORS as e:
                self._pump_s += time.monotonic() - t0
                self._kill_replica(rep, f"repin transport error: {e!r}")
                continue
            for rid, (base, _toks) in info.items():
                live_map.setdefault(int(rid), []).append(
                    (rep, int(base)))
        state_n, adopted, resubmitted = self._restore_requests(live_map)
        bump_counter("fleet.takeover")
        telemetry.flight_recorder().record(
            "takeover", fence=fence, requests=state_n, adopted=adopted,
            resubmitted=resubmitted)
        if telemetry.enabled():
            for freq in self._requests.values():
                # hops across the LEADERSHIP boundary stitch too: the new
                # leader's fresh trace ids are announced against the rids
                telemetry.trace_event("fleet.takeover_adopt",
                                      trace=freq.trace, rid=freq.rid,
                                      fence=fence)
        logger.warning(
            "takeover complete (fence %d): %d journaled request(s) — "
            "%d running cop(ies) adopted, %d resubmitted", fence,
            state_n, adopted, resubmitted)
        return {"fence": fence, "requests": state_n,
                "adopted": adopted, "resubmitted": resubmitted}

    def _restore_requests(self, live_map) -> tuple:
        """Rebuild the request table from the journal's live state —
        the shared tail of a hot-standby promotion (``live_map`` from
        the re-pin handshake) and a restart-in-place recovery (empty
        ``live_map``: nothing is running anywhere, everything parks or
        resubmits). Seeds the rid counter past every journaled rid so a
        restarted router cannot alias one. Returns (journaled, adopted,
        resubmitted)."""
        state = self._journal.live_state()
        self._rids = itertools.count(
            max(self._journal.max_rid() + 1, next(self._rids)))
        adopted = resubmitted = 0
        for rid, rec in sorted(state.items()):
            remaining = None
            if rec["deadline_s"] is not None:
                remaining = (rec["deadline_s"]
                             - (time.time() - rec["admit_wall"]))  # wall-clock: x-process replay
            freq = _FleetRequest(rid, rec["prompt"], rec["max_new"],
                                 rec["prio"], Deadline(remaining),
                                 rec["hedge"],
                                 deadline_s=rec["deadline_s"],
                                 tenant=rec.get("tenant"))
            freq.emitted = np.asarray(rec["emitted"], np.int32)
            self._requests[rid] = freq
            # re-establish the tenant's quota hold for the recovered
            # request (released again at _deliver)
            self._tenant_out[freq.tenant] = (
                self._tenant_out.get(freq.tenant, 0)
                + int(freq.prompt.size) + freq.max_new_tokens)
            for rep, base in live_map.get(rid, ()):
                if base <= len(freq.emitted):
                    # the running copy's stream offset is inside our
                    # known prefix: keep it — its terminal result
                    # recombines exactly via token_base
                    freq.live.add(rep.id)
                    rep.assigned.add(rid)
                    adopted += 1
                else:
                    # the copy resumed past a checkpoint we lost:
                    # cancel it and replay from what we know (the
                    # discard row is swallowed in _collect)
                    try:
                        rep.frontend.cancel(rid)
                    except StaleLeaderError:
                        # a concurrent higher-fence takeover outranks
                        # this one mid-promotion: abort (take_over's
                        # except rolls us back to standby) — counting
                        # this as a mere cancel error would let the
                        # LOSER finish promoting and double-dispatch
                        raise
                    except _TRANSPORT_ERRORS as e:
                        self._kill_replica(
                            rep, f"cancel transport error: {e!r}")
                        continue
                    except Exception:  # noqa: BLE001 — replica-local
                        bump_counter("fleet.cancel_error")
                    rep.assigned.add(rid)
                    freq.live.add(rep.id)
                    freq.discard.add(rep.id)
                    freq.excluded.add(rep.id)
            ho = rec.get("handoff")
            if ho is not None:
                # the dead leader crashed MID-HANDOFF for this rid:
                # prefill done, decode dispatch not yet acked (the
                # window the HANDOFF record exists for)
                if freq.live - freq.discard:
                    # a live copy survived after all (the decode
                    # dispatch raced the crash): the hop completed —
                    # clear it so a later takeover won't re-drive it
                    self._journal.handoff_done(rid)
                elif self._redrive_handoff(freq, ho):
                    resubmitted += 1
                    continue
            if not (freq.live - freq.discard):
                if freq.discard:
                    continue  # replay resumes when the discard row lands
                resubmitted += 1
                if not self._dispatch(freq):
                    self._parked.append(rid)
        return len(state), adopted, resubmitted

    def _redrive_handoff(self, freq, ho) -> bool:
        """Resume one journaled mid-handoff hop after takeover. The
        source's ``export_pages`` is rid-idempotent — the dead leader
        never released the hold, so re-asking returns the SAME ticket
        and the hop re-drives exactly once. Returns False when the
        pages are gone (dead/respawned source): the caller re-prefills
        from the journaled prefix instead — first token included, so
        the stream is still bit-identical."""
        if (ho.get("first_token") is not None
                and not len(freq.emitted)):
            # the HANDOFF record outlives any progress checkpoint for
            # the first token: seed it so even the re-prefill path
            # resumes mid-stream instead of recomputing
            freq.emitted = np.asarray([ho["first_token"]], np.int32)
        src = self._replicas.get(ho.get("source"))
        ticket = None
        if src is not None and src.state == "up":
            try:
                ticket = src.frontend.export_pages(freq.rid)
            except StaleLeaderError:
                # a concurrent higher-fence takeover outranks this one
                # mid-promotion: abort (take_over rolls back to standby)
                raise
            except _TRANSPORT_ERRORS as e:
                self._kill_replica(
                    src, f"handoff re-export transport error: {e!r}")
        if ticket is None:
            # pages gone (source dead, respawned, or hold released):
            # clear the hop; the normal resubmit path re-prefills
            bump_counter("fleet.handoff_reprefill")
            if len(freq.emitted):
                self._journal.progress(freq.rid, freq.emitted)
            self._journal.handoff_done(freq.rid)
            freq.phase = None
            return False
        freq.phase = "decode"
        self._transfers[freq.rid] = {"ticket": ticket, "source": src.id}
        bump_counter("fleet.handoff_redriven")
        if telemetry.enabled():
            _M_XFER_TICKET.set(1, rid=str(freq.rid),
                               ticket=str(ticket["ticket"])[:8],
                               src=str(src.id))
            _M_XFER_INFLIGHT.set(len(self._transfers))
        self._advance_handoff(freq)
        return True

    # ------------------------------------------------------------ admin

    def attach_autoscaler(self, scaler):
        """Wire an ``models/autoscale.AutoScaler`` into the pump: every
        ``step()`` gives its (rate-limited) control loop a turn, so a
        fleet that is being pumped sizes itself without a separate
        driver thread. Returns the scaler for chaining."""
        self._autoscaler = scaler
        return scaler

    def warmup(self, cache_dir=None):
        """AOT-warm every replica's compiled serving shapes. A replica
        whose warmup fails at the TRANSPORT is classified dead (like any
        other call) rather than aborting the remaining replicas'
        warmups with the corpse left registered as up."""
        out = {}
        for rep in list(self._replicas.values()):
            if rep.state != "up":
                continue
            try:
                out[rep.id] = rep.frontend.warmup(cache_dir=cache_dir)
            except StaleLeaderError as e:
                self._stand_down(str(e))
                return out
            except _TRANSPORT_ERRORS as e:
                self._kill_replica(rep, f"warmup transport error: {e!r}")
        return out

    def shutdown(self, drain=True):
        """Drain (or hard-stop) every replica and deliver what resolves;
        anything still pending afterwards delivers ``unavailable``.

        A GRACEFUL shutdown also hands leadership over cleanly: the
        leader lease is RELEASED (deleted, not left to expire — a hot
        standby takes over in ~0 instead of waiting out a full ttl) and
        the router's own store keys (published heartbeat cadence,
        membership registry) are deleted so nothing stale outlives it."""
        for rep in list(self._replicas.values()):
            if rep.state == "up":
                with contextlib.suppress(Exception):
                    rep.frontend.shutdown(drain=drain)
                rep.state = "draining"
                self._collect(rep)
            self._deregister(rep)
        for freq in list(self._requests.values()):
            self._deliver(freq, "unavailable", freq.emitted,
                          "fleet shutdown")
        for rep in self._replicas.values():
            self._absorb_rpc_stats(rep)
            if telemetry.enabled():
                self._retire_replica_gauges(rep)
        self._replicas.clear()
        if self._detector is not None:
            with contextlib.suppress(Exception):
                self._detector.stop()
        if self._journal is not None:
            with contextlib.suppress(Exception):
                self._journal.close()
        if (self._store is not None and not self._standby
                and not self._deposed):
            # the LEADER's own keys must not linger: a stale hb_interval
            # would re-pace the next fleet epoch's replicas, and a stale
            # membership registry would have a future standby adopting
            # corpses. A standby/deposed router shutting down owns
            # neither key — deleting them here would clobber the live
            # leader's published state
            for key in (f"{self._prefix}/hb_interval",
                        f"{self._prefix}/members"):
                with contextlib.suppress(Exception):
                    self._store.delete_key(key)
        if self._llease is not None:
            # release, not expire: the standby's wait_acquire returns
            # the moment the record disappears
            with contextlib.suppress(Exception):
                self._llease.release()

    def _member_metric_snapshots(self) -> list:
        """Registry snapshots the replica PROCESSES published to the
        gang store on their heartbeat cadence (``replica_main``), for
        the current remote membership. In-process replicas share this
        process's registry and need no store hop."""
        snaps = []
        if self._store is None:
            return snaps
        for rep in list(self._replicas.values()):
            if rep.state == "dead":
                continue
            if not getattr(rep.frontend, "is_remote", False):
                continue
            key = f"{self._prefix}/metrics/{rep.id}"
            try:
                if self._store.check(key):
                    snaps.append(
                        json.loads(self._store.get_now(key).decode()))
            except (ValueError, KeyError, RuntimeError, ConnectionError,
                    TimeoutError):
                bump_counter("fleet.metrics_unreadable")
        return snaps

    _STATE_CODE = {"up": 1, "draining": 2, "dead": 0}
    _BREAKER_CODE = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                     CircuitBreaker.OPEN: 2}

    def _retire_replica_gauges(self, rep):
        """Final gauge export for a replica LEAVING the table (scale-in,
        shutdown): without it the last exported state ('up') freezes in
        every later snapshot and the roster lists the departed replica
        as alive forever."""
        rid = str(rep.id)
        _M_REP_STATE.set(0, replica=rid)
        _M_REP_ASSIGNED.set(0, replica=rid)

    def _export_replica_gauges(self):
        """Mirror the per-replica membership view (state, breaker,
        assignment, heartbeat age, incarnation) into labeled gauges so
        any snapshot of this registry carries the fleet roster — what
        ``obs fleet`` renders offline from a saved snapshot or a flight
        dump, when the live router is exactly the thing that died."""
        for rep in list(self._replicas.values()):
            rid = str(rep.id)
            _M_REP_STATE.set(self._STATE_CODE.get(rep.state, -1),
                             replica=rid)
            _M_REP_BREAKER.set(
                self._BREAKER_CODE.get(rep.breaker.state(), -1),
                replica=rid)
            _M_REP_ASSIGNED.set(len(rep.assigned), replica=rid)
            _M_REP_SERVED.set(rep.served, replica=rid)
            _M_REP_ROLE.set(1, replica=rid, role=rep.role)
            inc = (rep.h_cache or {}).get("_inc")
            if inc:
                _M_REP_INC.set(1, replica=rid, inc=str(inc)[:8])
            if self._store is not None and rep.state != "dead":
                with contextlib.suppress(Exception):
                    t = self._store.last_heartbeat(
                        rep.id, prefix=f"{self._prefix}/hb")
                    if t is not None:
                        _M_REP_HB_AGE.set(
                            max(time.time() - t, 0.0),  # wall-clock: x-process store beats
                            replica=rid)

    def fleet_metrics(self) -> dict:
        """ONE fleet-wide observability view: this process's telemetry
        registry merged with every replica process's store-published
        snapshot (``telemetry.merge_snapshots``). Answers the operator
        question in one call:

        * ``latency`` — fleet-wide TTFT / per-token / queue-wait
          p50/p95/p99 (merged histograms);
        * ``tokens_total`` and ``tokens_per_sec`` (rate over the window
          since the previous ``fleet_metrics()`` call);
        * ``replicas`` — per-replica state + router-side breaker state;
        * ``phases`` — fleet-wide step-time attribution (perfwatch
          ``serving.phase_s`` percentiles per scheduler phase);
        * ``slo`` — the declared TTFT/per-token objectives evaluated
          over the merged histograms (rolling goodput + multi-window
          burn rate + alarm);
        * ``tenants`` — per-tenant QoS view (TTFT/token/queue-wait
          percentiles, goodput at the TTFT objective, tokens served,
          shed/rejected/quota counts) from the tenant-labeled series;
        * ``brownout_stage`` — the brownout ladder stage from the
          merged ``serving.brownout_stage`` gauge (freshest snapshot
          wins — in an in-process fleet this is THE stage);
        * ``metrics`` — the full merged snapshot (counters incl. the
          whole resilience ledger, gauges, histograms) for export.
        """
        if telemetry.enabled():
            # refresh the roster gauges BEFORE snapshotting, so the
            # merged view (and anything that saves it) carries them
            self._export_replica_gauges()
        merged = telemetry.merge_snapshots(
            telemetry.registry().snapshot(),
            *self._member_metric_snapshots())
        tokens = merged["counters"].get("serving.tokens_total", 0)
        now = time.monotonic()
        rate = 0.0
        if self._fm_prev is not None:
            pt, pts = self._fm_prev
            if now > pts and tokens >= pt:
                rate = (tokens - pt) / (now - pts)
        self._fm_prev = (tokens, now)
        self._last_fleet = merged
        if self._slo_fleet is None:
            self._slo_fleet = perfwatch.SLOMonitor(
                source=lambda: self._last_fleet)
        return {
            "metrics": merged,
            "latency": latency_summaries(merged),
            # perfwatch: fleet-wide step-time attribution + SLO verdict
            # over the merged histograms
            "phases": (perfwatch.phase_summaries(merged)
                       if telemetry.enabled() else {}),
            "slo": (self._slo_fleet.status()
                    if telemetry.enabled() else {}),
            "tenants": (tenant_summaries(merged)
                        if telemetry.enabled() else {}),
            "brownout_stage": int(merged["gauges"].get(
                "serving.brownout_stage", 0)),
            "tokens_total": tokens,
            "tokens_per_sec": rate,
            "replicas": {r.id: {"state": r.state,
                                "breaker": r.breaker.state(),
                                "breaker_failures": r.breaker.failures,
                                "assigned": len(r.assigned),
                                "served": r.served,
                                "role": r.role}
                         for r in self._replicas.values()},
            "transfers_inflight": len(self._transfers),
            "pending": len(self._requests),
            "role": ("standby" if self._standby
                     else "deposed" if self._deposed else "leader"),
        }

    def health(self) -> dict:
        """Fleet-level snapshot: per-replica health + aggregate load."""
        reps = {}
        for rep in self._replicas.values():
            try:
                h = rep.frontend.health() if rep.state == "up" else {}
            except Exception:
                h = {}
            reps[rep.id] = {"state": rep.state,
                            "breaker": rep.breaker.state(),
                            "assigned": len(rep.assigned), **h}
        up = [r for r in self._replicas.values() if r.state == "up"]
        return {
            "replicas": reps,
            "up": len(up),
            "total": len(self._replicas),
            "pending": len(self._requests),
            "parked": len(self._parked),
            "ready": bool(up) and not self._standby and not self._deposed,
            "role": ("standby" if self._standby
                     else "deposed" if self._deposed else "leader"),
        }

    def stats(self) -> dict:
        """Router-side accounting. ``router_overhead_pct`` is the share
        of ACTIVE request-processing time spent in routing/bookkeeping
        outside the replica frontends — ``route_s / (route_s + pump_s)``,
        deliberately NOT route/wall: wall includes warmup and idle time,
        which would let an arbitrarily slow routing path pass the gate.
        The fleet acceptance gate records it as
        ``fleet_router_overhead_pct`` (< 5%).

        For a fleet of REMOTE replicas the same split also yields the
        transport gate: ``rpc_s`` is round-trip time inside
        ``RemoteFrontend`` calls, ``remote_exec_s`` the server-side
        execution those calls reported, and ``rpc_overhead_pct`` =
        (rpc_s − remote_exec_s) / active — wire+serialization time as a
        share of active processing (bench e3 gates it as
        ``fleet_rpc_overhead_pct`` < 10%)."""
        wall = time.monotonic() - self._t0
        active = self._route_s + self._pump_s
        rpc = dict(self._rpc_retired)
        for rep in self._replicas.values():
            self._fold_rpc_stats(rpc, rep.frontend)
        rpc_overhead = max(rpc["rpc_s"] - rpc["remote_exec_s"], 0.0)
        journal_s = (self._journal.write_s if self._journal is not None
                     else 0.0)
        return {
            "wall_s": wall,
            "route_s": self._route_s,
            "pump_s": self._pump_s,
            "router_overhead_pct": (100.0 * self._route_s / active
                                    if active > 0 else 0.0),
            # journal (WAL) cost as a share of active processing — the
            # bench e4 gate records it as router_journal_overhead_pct
            # (< 5%). journal_s is a SUBSET of route_s (appends happen
            # inside routing turns), split out for the gate.
            "journal_s": journal_s,
            "journal_overhead_pct": (100.0 * journal_s / active
                                     if active > 0 else 0.0),
            "rpc_s": rpc["rpc_s"],
            "remote_exec_s": rpc["remote_exec_s"],
            "rpc_calls": rpc["calls"],
            "rpc_overhead_s": rpc_overhead,
            "rpc_overhead_pct": (100.0 * rpc_overhead / active
                                 if active > 0 else 0.0),
            "replicas_up": sum(1 for r in self._replicas.values()
                               if r.state == "up"),
            "served_by_replica": {r.id: r.served
                                  for r in self._replicas.values()},
            # TTFT / per-token / queue-wait p50/p95/p99 from the registry
            # histograms: in-process fleets observe everything locally;
            # a fleet with REMOTE replicas reads the last fleet_metrics()
            # merge (the replica processes own the observations)
            "latency": latency_summaries(
                self._last_fleet
                if self._last_fleet is not None
                and any(getattr(r.frontend, "is_remote", False)
                        for r in self._replicas.values())
                else None),
            **{f"requests_{k}": v for k, v in sorted(self._counts.items())},
        }


def launch_fleet(entry, n_replicas, entry_args=(), max_restarts=3,
                 **launch_kwargs):
    """Run ``entry`` as ``n_replicas`` replica worker processes under the
    ``launch()`` supervisor with the serving failure domain:
    ``restart_policy="worker"`` (a crashed replica respawns ALONE within
    the restart budget while the survivors keep serving) and the
    supervisor's gang store exported for fleet heartbeats."""
    from ..distributed.launch import launch

    return launch(entry, entry_args=entry_args,
                  nproc_per_node=n_replicas, max_restarts=max_restarts,
                  restart_policy="worker", **launch_kwargs)
