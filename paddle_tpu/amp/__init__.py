"""paddle_tpu.amp — automatic mixed precision.

Analog of /root/reference/python/paddle/amp/ (auto_cast.py, grad_scaler.py,
amp_lists.py). bf16 is the TPU-native low dtype (no loss scaling needed);
fp16 + GradScaler are provided for reference parity.
"""
from . import amp_lists  # noqa: F401
from . import debugging  # noqa: F401
from .auto_cast import (  # noqa: F401
    amp_decorate,
    amp_guard,
    amp_state,
    auto_cast,
    decorate,
)
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401

# install the cast hook into the eager dispatcher
from ..ops import registry as _registry
from .auto_cast import _state as _amp_state
from .auto_cast import amp_transform_arguments as _amp_transform

_registry.install_amp(_amp_state, _amp_transform)


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


__all__ = [
    "auto_cast", "amp_guard", "decorate", "amp_decorate", "GradScaler",
    "AmpScaler", "amp_lists", "is_bfloat16_supported", "is_float16_supported",
]
