"""Op-tail kernels — the remaining reference base-yaml surface.

Closes the gap against /root/reference/paddle/phi/ops/yaml/ops.yaml (467
base ops): activations, losses, pooling/interp variants, signal framing,
detection/box utilities, fake-quantization, AMP bookkeeping and functional
optimizer-update ops. Pure jnp compositions — XLA fuses these; the hot
fused paths live in ops/pallas/. Reference kernel anchors cited per
function.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _random

# ------------------------------------------------------------ activations


def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


def tanh_shrink(x):
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, *,
          rng_key=None):
    """paddle/phi/kernels/gpu/rrelu_kernel.cu: random leaky slope in
    [lower, upper) per element when training, mean slope in eval."""
    if not training:
        return jnp.where(x >= 0, x, x * ((lower + upper) / 2.0))
    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    a = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
    return jnp.where(x >= 0, x, x * a.astype(x.dtype))


def swiglu(x, y=None):
    """phi/kernels/gpu/swiglu_kernel.cu: silu(x) * y (y defaults to the
    second half of x split on the last dim)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


# ------------------------------------------------------------ reductions


def mean_all(x):
    return jnp.mean(x)


def numel(x):
    return jnp.asarray(np.prod(x.shape) if x.shape else 1, jnp.int64)


def shape(x):
    return jnp.asarray(x.shape, jnp.int32)


def is_empty(x):
    return jnp.asarray(x.size == 0)


def l1_norm(x):
    return jnp.sum(jnp.abs(x))


def squared_l2_norm(x):
    return jnp.sum(jnp.square(x))


def frobenius_norm(x, axis=None, keepdim=False):
    if axis is None:
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=tuple(axis),
                            keepdims=keepdim))


def clip_by_norm(x, max_norm):
    """phi/kernels/impl/clip_by_norm_kernel_impl.h: scale down to L2 norm
    max_norm."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))
    scale = max_norm / jnp.maximum(norm, max_norm)
    return (x.astype(jnp.float32) * scale).astype(x.dtype)


# ------------------------------------------------------------ creation/view


def fill(x, value=0.0):
    return jnp.full_like(x, value)


def fill_diagonal(x, value=0.0, offset=0, wrap=False):
    eye = jnp.eye(x.shape[-2], x.shape[-1], k=offset, dtype=bool)
    return jnp.where(eye, jnp.asarray(value, x.dtype), x)


def empty(shape, dtype="float32"):
    from ..core.dtype import to_jax_dtype

    return jnp.zeros(tuple(shape), to_jax_dtype(dtype))


def empty_like(x, dtype=None):
    from ..core.dtype import to_jax_dtype

    return jnp.zeros_like(x, dtype=to_jax_dtype(dtype) if dtype else None)


def reverse(x, axis):
    ax = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, ax)


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """phi/kernels/sequence_mask_kernel: mask[i, j] = j < lengths[i]."""
    from ..core.dtype import to_jax_dtype

    if maxlen is None or maxlen < 0:
        maxlen = int(jnp.max(lengths))
    cols = jnp.arange(maxlen)
    mask = cols[None, :] < lengths.reshape(-1, 1)
    return mask.reshape(*lengths.shape, maxlen).astype(to_jax_dtype(dtype))


def share_data(x):
    return x


def split_with_num(x, num, axis=0):
    return tuple(jnp.split(x, num, axis=axis))


def partial_sum(inputs, start_index=0, length=-1):
    """operators/partial_sum_op: sum of column slices of 2-D inputs."""
    sl = [v[:, start_index:(None if length < 0 else start_index + length)]
          for v in inputs]
    return sum(sl[1:], sl[0])


def partial_concat(inputs, start_index=0, length=-1):
    sl = [v[:, start_index:(None if length < 0 else start_index + length)]
          for v in inputs]
    return jnp.concatenate(sl, axis=1)


# ------------------------------------------------------------ losses


def hinge_loss(logits, labels):
    """operators/hinge_loss_op: max(1 - logits*(2*labels-1), 0)."""
    return jnp.maximum(1.0 - logits * (2.0 * labels - 1.0), 0.0)


def huber_loss(input, label, delta=1.0):
    r = input - label
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))


def log_loss(input, label, epsilon=1e-4):
    return (-label * jnp.log(input + epsilon)
            - (1.0 - label) * jnp.log(1.0 - input + epsilon))


def sigmoid_cross_entropy_with_logits(x, label, normalize=False,
                                      ignore_index=-100):
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    valid = label != ignore_index
    loss = jnp.where(valid, loss, 0.0)
    if normalize:
        loss = loss / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return loss


def identity_loss(x, reduction=1):
    # reduction: 0=sum 1=mean 2=none (phi/kernels/identity_loss_kernel)
    if reduction == 0:
        return jnp.sum(x)
    if reduction == 1:
        return jnp.mean(x)
    return x


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, return_softmax=False):
    """phi/kernels/margin_cross_entropy_kernel (single-rank case):
    cos(m1*theta + m2) - m3 margin applied to the target logit."""
    theta = jnp.arccos(jnp.clip(logits, -1.0, 1.0))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    onehot = jax.nn.one_hot(label, logits.shape[-1], dtype=logits.dtype)
    adjusted = scale * jnp.where(onehot > 0, target, logits)
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(onehot * logp, axis=-1, keepdims=True)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def accuracy(out, indices, label):
    """phi/kernels/accuracy_kernel: fraction of rows whose top-k `indices`
    contain the label. Returns (accuracy, correct, total)."""
    hit = jnp.any(indices == label.reshape(-1, 1), axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.asarray(indices.shape[0], jnp.int32)
    return (correct.astype(jnp.float32) / total.astype(jnp.float32),
            correct, total)


def auc(predict, label, num_thresholds=4095):
    """phi/kernels/auc_kernel: ROC-AUC via thresholded confusion counts."""
    pos_score = predict[:, 1] if predict.ndim == 2 else predict
    buckets = jnp.clip((pos_score * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
    lab = label.reshape(-1).astype(jnp.float32)
    pos_hist = jnp.zeros(num_thresholds + 1).at[buckets].add(lab)
    neg_hist = jnp.zeros(num_thresholds + 1).at[buckets].add(1.0 - lab)
    # descending-threshold cumulative TP/FP
    tp = jnp.cumsum(pos_hist[::-1])
    fp = jnp.cumsum(neg_hist[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    # trapezoid over the ROC curve
    area = jnp.sum((fp[1:] - fp[:-1]) * (tp[1:] + tp[:-1]) / 2.0)
    area = area + tp[0] * fp[0] / 2.0  # first segment from (0,0)
    return area / jnp.maximum(tot_pos * tot_neg, 1e-12)


# ------------------------------------------------------------ random


def dirichlet(alpha, *, rng_key=None):
    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    return jax.random.dirichlet(key, alpha)


def truncated_gaussian_random(shape, mean=0.0, std=1.0, a=-2.0, b=2.0,
                              dtype="float32", *, rng_key=None):
    from ..core.dtype import to_jax_dtype

    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    z = jax.random.truncated_normal(key, a, b, tuple(shape),
                                    to_jax_dtype(dtype))
    return z * std + mean


def exponential_(x, lam=1.0, *, rng_key=None):
    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    return jax.random.exponential(key, x.shape, x.dtype) / lam


def uniform_inplace(x, min=-1.0, max=1.0, *, rng_key=None):
    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    return jax.random.uniform(key, x.shape, x.dtype, min, max)


def gaussian_inplace(x, mean=0.0, std=1.0, *, rng_key=None):
    key = (jax.random.wrap_key_data(rng_key) if rng_key is not None
           else _random.next_key())
    return jax.random.normal(key, x.shape, x.dtype) * std + mean


# ------------------------------------------------------------ quantization


def fake_quantize_abs_max(x, bit_length=8):
    """phi/kernels/fake_quantize_kernels: symmetric per-tensor quantize.
    Returns (quantized, scale)."""
    qmax = float((1 << (bit_length - 1)) - 1)
    scale = jnp.max(jnp.abs(x))
    q = jnp.round(x / jnp.maximum(scale, 1e-12) * qmax)
    return jnp.clip(q, -qmax, qmax), scale.reshape(1)


def fake_quantize_dequantize_abs_max(x, bit_length=8):
    q, scale = fake_quantize_abs_max(x, bit_length)
    qmax = float((1 << (bit_length - 1)) - 1)
    return q * scale[0] / qmax, scale


def fake_channel_wise_quantize_abs_max(x, bit_length=8, quant_axis=0):
    qmax = float((1 << (bit_length - 1)) - 1)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-12) * qmax), -qmax, qmax)
    return q, scale.reshape(-1)


def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0):
    qmax = float((1 << (bit_length - 1)) - 1)
    q, scale = fake_channel_wise_quantize_abs_max(x, bit_length, quant_axis)
    shape = [1] * x.ndim
    shape[quant_axis] = -1
    return q * scale.reshape(shape) / qmax, scale


def fake_dequantize_max_abs(x, scale, max_range):
    return x * scale / max_range


def dequantize_abs_max(x, scale, max_range):
    return x.astype(jnp.float32) * scale / max_range


# ------------------------------------------------------------ AMP ops


def check_finite_and_unscale_(xs, scale):
    """phi/kernels/check_finite_and_unscale_kernel: divide grads by scale,
    flag non-finite. Returns (unscaled..., found_inf)."""
    inv = 1.0 / scale
    found = jnp.asarray(False)
    outs = []
    for x in xs:
        y = x.astype(jnp.float32) * inv
        found = found | ~jnp.all(jnp.isfinite(y))
        outs.append(y.astype(x.dtype))
    return (*outs, found)


def update_loss_scaling_(scale, found_inf, good_steps, bad_steps=None,
                         incr_every_n_steps=2000,
                         decr_every_n_nan_or_inf=1, incr_ratio=2.0,
                         decr_ratio=0.5):
    """phi/kernels/update_loss_scaling_kernel: dynamic loss-scale update.
    Decreases only after ``decr_every_n_nan_or_inf`` consecutive bad steps
    (tracked by ``bad_steps``), increases after ``incr_every_n_steps``
    consecutive good ones. Returns (new_scale, new_good, new_bad)."""
    if bad_steps is None:
        bad_steps = jnp.zeros_like(good_steps)
    new_bad = jnp.where(found_inf, bad_steps + 1, 0)
    shrink = new_bad >= decr_every_n_nan_or_inf
    grew = (~found_inf) & (good_steps + 1 >= incr_every_n_steps)
    new_scale = jnp.where(
        shrink, jnp.maximum(scale * decr_ratio, 1.0),
        jnp.where(grew, scale * incr_ratio, scale))
    new_good = jnp.where(found_inf | grew, 0, good_steps + 1)
    new_bad = jnp.where(shrink, 0, new_bad)
    return new_scale, new_good, new_bad


# ------------------------------------------------------- optimizer updates


def sgd_(param, learning_rate, grad):
    return param - learning_rate * grad


def momentum_(param, grad, velocity, learning_rate, mu=0.9,
              use_nesterov=False):
    v = mu * velocity + grad
    if use_nesterov:
        return param - learning_rate * (grad + mu * v), v
    return param - learning_rate * v, v


def adam_(param, grad, moment1, moment2, beta1_pow, beta2_pow,
          learning_rate, beta1=0.9, beta2=0.999, epsilon=1e-8):
    m = beta1 * moment1 + (1 - beta1) * grad
    v = beta2 * moment2 + (1 - beta2) * grad * grad
    mhat = m / (1 - beta1_pow)
    vhat = v / (1 - beta2_pow)
    new_p = param - learning_rate * mhat / (jnp.sqrt(vhat) + epsilon)
    return new_p, m, v, beta1_pow * beta1, beta2_pow * beta2


def adamw_(param, grad, moment1, moment2, beta1_pow, beta2_pow,
           learning_rate, beta1=0.9, beta2=0.999, epsilon=1e-8,
           weight_decay=0.01):
    p = param * (1 - learning_rate * weight_decay)
    return adam_(p, grad, moment1, moment2, beta1_pow, beta2_pow,
                 learning_rate, beta1, beta2, epsilon)


def adagrad_(param, grad, moment, learning_rate, epsilon=1e-6):
    m = moment + grad * grad
    return param - learning_rate * grad / (jnp.sqrt(m) + epsilon), m


def rmsprop_(param, grad, mean_square, learning_rate, rho=0.95,
             epsilon=1e-6, momentum=0.0):
    ms = rho * mean_square + (1 - rho) * grad * grad
    return param - learning_rate * grad / jnp.sqrt(ms + epsilon), ms


def merged_momentum_(params, grads, velocities, learning_rate, mu=0.9,
                     use_nesterov=False):
    outs = [momentum_(p, g, v, learning_rate, mu, use_nesterov)
            for p, g, v in zip(params, grads, velocities)]
    return tuple(o[0] for o in outs), tuple(o[1] for o in outs)


# ------------------------------------------------------------ structure


def pixel_unshuffle(x, downscale_factor=2, data_format="NCHW"):
    r = downscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        return x.transpose(0, 1, 3, 5, 2, 4).reshape(
            n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // r, w // r, c * r * r)


def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w).transpose(
            0, 2, 1, 3, 4).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups).transpose(
        0, 1, 2, 4, 3).reshape(n, h, w, c)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """phi/kernels/temporal_shift_kernel: shift 1/4 channels fwd, 1/4 bwd
    along the segment (time) axis."""
    if data_format != "NCHW":
        x = x.transpose(0, 3, 1, 2)
    nt, c, h, w = x.shape
    n = nt // seg_num
    v = x.reshape(n, seg_num, c, h, w)
    c1 = int(c * shift_ratio)
    c2 = int(c * 2 * shift_ratio)
    pad = jnp.zeros_like(v[:, :1])
    fwd = jnp.concatenate([v[:, 1:, :c1], pad[:, :, :c1]], axis=1)
    bwd = jnp.concatenate([pad[:, :, c1:c2], v[:, :-1, c1:c2]], axis=1)
    keep = v[:, :, c2:]
    out = jnp.concatenate([fwd, bwd, keep], axis=2).reshape(nt, c, h, w)
    if data_format != "NCHW":
        out = out.transpose(0, 2, 3, 1)
    return out


def add_position_encoding(x, alpha=1.0, beta=1.0):
    """operators/add_position_encoding_op: sinusoidal PE added to (B,S,H)."""
    b, s, h = x.shape
    pos = np.arange(s, dtype=np.float64)[:, None]
    div = np.power(10000.0, 2 * (np.arange(h // 2, dtype=np.float64)) / h)
    ang = pos / div
    pe = np.concatenate([np.sin(ang), np.cos(ang)], axis=1)
    return alpha * x + beta * jnp.asarray(pe, x.dtype)[None]


def bilinear(x, y, weight, bias=None):
    """phi/kernels/bilinear_kernel: out[b, o] = x[b] @ W[o] @ y[b]."""
    out = jnp.einsum("bi,oij,bj->bo", x, weight, y)
    if bias is not None:
        out = out + bias
    return out


def affine_channel(x, scale, bias, data_format="NCHW"):
    if data_format == "NCHW":
        return x * scale.reshape(1, -1, 1, 1) + bias.reshape(1, -1, 1, 1)
    return x * scale + bias


def fused_softmax_mask(x, mask):
    return jax.nn.softmax(x + mask, axis=-1)


def fused_softmax_mask_upper_triangle(x):
    s = x.shape[-1]
    rows = jnp.arange(x.shape[-2])[:, None]
    cols = jnp.arange(s)[None, :]
    return jax.nn.softmax(jnp.where(cols <= rows, x, -1e9), axis=-1)


def gather_tree(ids, parents):
    """phi/kernels/gather_tree_kernel: beam-search backtrace.
    ids/parents: (max_time, batch, beam)."""
    max_time = ids.shape[0]

    def step(carry, t):
        beams = carry  # (batch, beam) active parent pointers
        out = jnp.take_along_axis(ids[t], beams, axis=1)
        new_beams = jnp.take_along_axis(parents[t], beams, axis=1)
        return new_beams, out

    init = jnp.tile(jnp.arange(ids.shape[2])[None, :], (ids.shape[1], 1))
    _, outs = jax.lax.scan(step, init, jnp.arange(max_time - 1, -1, -1))
    return outs[::-1]


# ------------------------------------------------------------ pool/interp


def pool2d(x, kernel_size, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, adaptive=False,
           data_format="NCHW"):
    """Generic pool2d op (phi/kernels/pool_kernel): routes to the existing
    max/avg/adaptive pooling kernels by attribute, like the reference's
    single pool2d op with a pooling_type attr."""
    from . import nn_kernels as _nn

    if adaptive:
        if pooling_type == "max":
            return _nn.adaptive_max_pool2d(x, kernel_size)
        return _nn.adaptive_avg_pool2d(x, kernel_size)
    if pooling_type == "max":
        return _nn.max_pool2d(x, kernel_size, stride=stride, padding=padding,
                              ceil_mode=ceil_mode)
    return _nn.avg_pool2d(x, kernel_size, stride=stride, padding=padding,
                          ceil_mode=ceil_mode, exclusive=exclusive)


def _pool_nd(x, kernel_size, stride, padding, nd, op, init, ceil_mode=False):
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * nd
    if stride is None:
        stride = kernel_size
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(padding, int):
        padding = (padding,) * nd
    window = (1, 1) + tuple(kernel_size)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple(
        (p, p + (s - 1 if ceil_mode else 0))
        for p, s in zip(padding, stride))
    return jax.lax.reduce_window(x, init, op, window, strides, pads)


def pool3d(x, kernel_size, stride=None, padding=0, pooling_type="max",
           ceil_mode=False, exclusive=True, adaptive=False,
           data_format="NCDHW"):
    if pooling_type == "max":
        return _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.max,
                        -jnp.inf, ceil_mode)
    s = _pool_nd(x, kernel_size, stride, padding, 3, jax.lax.add, 0.0,
                 ceil_mode)
    ones = _pool_nd(jnp.ones_like(x), kernel_size, stride, padding, 3,
                    jax.lax.add, 0.0, ceil_mode)
    if exclusive:
        return s / ones
    k = kernel_size if isinstance(kernel_size, int) else int(
        np.prod(kernel_size))
    k = k ** 3 if isinstance(kernel_size, int) else k
    return s / k


def lp_pool2d(x, kernel_size, stride=None, padding=0, norm_type=2.0,
              ceil_mode=False, data_format="NCHW"):
    p = float(norm_type)
    s = _pool_nd(jnp.abs(x) ** p, kernel_size, stride, padding, 2,
                 jax.lax.add, 0.0, ceil_mode)
    return s ** (1.0 / p)


def _pool_with_index(x, kernel_size, stride, padding, nd):
    """Max pool that also returns flat spatial argmax indices (reference
    max_pool2d_with_index / max_pool3d_with_index)."""
    if isinstance(kernel_size, int):
        kernel_size = (kernel_size,) * nd
    if stride is None:
        stride = kernel_size
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(padding, int):
        padding = (padding,) * nd
    spatial = x.shape[2:]
    flat_idx = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)
    window = (1, 1) + tuple(kernel_size)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in padding)

    def sel(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    out, idx = jax.lax.reduce_window(
        (x, flat_idx), (-jnp.inf, jnp.asarray(0)), sel,
        window, strides, pads)
    return out, idx


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False):
    if global_pooling:
        kernel_size = x.shape[2:]
        stride, padding = None, 0
    return _pool_with_index(x, kernel_size, stride, padding, 2)


def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False):
    if global_pooling:
        kernel_size = x.shape[2:]
        stride, padding = None, 0
    return _pool_with_index(x, kernel_size, stride, padding, 3)


def unpool(x, indices, kernel_size=2, stride=None, padding=0,
           output_size=None, data_format="NCHW"):
    """phi/kernels/unpool_kernel: scatter pooled values back to the
    positions recorded by max_pool2d_with_index."""
    n, c = x.shape[:2]
    if output_size is None:
        if stride is None:
            stride = kernel_size
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        s = stride if isinstance(stride, int) else stride[0]
        output_size = [(d - 1) * s + k - 2 * padding for d in x.shape[2:]]
    out_spatial = int(np.prod(output_size))
    flat = jnp.zeros((n, c, out_spatial), x.dtype)
    idx = indices.reshape(n, c, -1)
    vals = x.reshape(n, c, -1)
    flat = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idx
    ].set(vals)
    return flat.reshape(n, c, *output_size)


def unpool3d(x, indices, kernel_size=2, stride=None, padding=0,
             output_size=None, data_format="NCDHW"):
    return unpool(x, indices, kernel_size, stride, padding, output_size)


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False):
    """phi/kernels/fractional_max_pool2d: pseudo-random pooling regions;
    deterministic alpha-sequence variant (random_u supplies the offset)."""
    n, c, h, w = x.shape
    oh, ow = ((output_size, output_size) if isinstance(output_size, int)
              else tuple(output_size))
    u = 0.5 if random_u is None else float(random_u)

    def edges(insz, outsz):
        alpha = insz / outsz
        idx = np.floor(alpha * (np.arange(outsz) + u)) - np.floor(alpha * u)
        idx = np.clip(idx.astype(np.int64), 0, insz - 1)
        ends = np.append(idx[1:], insz)
        return idx, ends

    hs, he = edges(h, oh)
    ws, we = edges(w, ow)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            cols.append(jnp.max(x[:, :, hs[i]:he[i], ws[j]:we[j]],
                                axis=(2, 3)))
        rows.append(jnp.stack(cols, axis=-1))
    return jnp.stack(rows, axis=-2)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False):
    n, c, d, h, w = x.shape
    od, oh, ow = ((output_size,) * 3 if isinstance(output_size, int)
                  else tuple(output_size))
    out = []
    u = 0.5 if random_u is None else float(random_u)
    alpha = d / od
    idx = np.floor(alpha * (np.arange(od) + u)) - np.floor(alpha * u)
    idx = np.clip(idx.astype(np.int64), 0, d - 1)
    ends = np.append(idx[1:], d)
    for i in range(od):
        sl = jnp.max(x[:, :, idx[i]:ends[i]], axis=2)
        out.append(fractional_max_pool2d(sl, (oh, ow), random_u=random_u))
    return jnp.stack(out, axis=2)


def depthwise_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     data_format="NCHW"):
    from . import nn_kernels as _nn

    return _nn.conv2d(x, weight, bias, stride=stride, padding=padding,
                      dilation=dilation, groups=x.shape[1],
                      data_format=data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW"):
    from .nn_kernels import grouped_conv_transpose_nd

    return grouped_conv_transpose_nd(x, weight, bias, stride, padding,
                                     output_padding, dilation, groups, nd=3)


def depthwise_conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                               output_padding=0, dilation=1,
                               data_format="NCHW"):
    from . import nn_kernels as _nn

    return _nn.conv2d_transpose(x, weight, bias, stride=stride,
                                padding=padding,
                                output_padding=output_padding,
                                dilation=dilation, groups=x.shape[1])


def _interp(x, size, scale_factor, mode, align_corners=False):
    from . import nn_kernels as _nn

    return _nn.interpolate(x, size=size, scale_factor=scale_factor,
                           mode=mode, align_corners=align_corners)


def bilinear_interp(x, size=None, scale_factor=None, align_corners=False):
    return _interp(x, size, scale_factor, "bilinear", align_corners)


def nearest_interp(x, size=None, scale_factor=None, align_corners=False):
    return _interp(x, size, scale_factor, "nearest", align_corners)


def bicubic_interp(x, size=None, scale_factor=None, align_corners=False):
    return _interp(x, size, scale_factor, "bicubic", align_corners)


def _linear_resize_last(x, out_w, align_corners):
    """1-D linear resample along the last axis, honoring align_corners."""
    in_w = x.shape[-1]
    if align_corners:
        # out_w == 1: ratio (in-1)/(out-1) is defined as 0 -> sample x[0]
        pos = (jnp.linspace(0.0, in_w - 1.0, out_w) if out_w > 1
               else jnp.zeros((1,)))
    else:
        pos = (jnp.arange(out_w) + 0.5) * (in_w / out_w) - 0.5
    lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, in_w - 1)
    hi = jnp.clip(lo + 1, 0, in_w - 1)
    w = jnp.clip(pos - lo, 0.0, 1.0).astype(x.dtype)
    return x[..., lo] * (1 - w) + x[..., hi] * w


def linear_interp(x, size=None, scale_factor=None, align_corners=False):
    # 3-D (N, C, W) input
    size = size if size is not None else (
        int(x.shape[-1] * scale_factor),)
    return _linear_resize_last(x, int(size[0]), align_corners)


def trilinear_interp(x, size=None, scale_factor=None, align_corners=False):
    # 5-D (N, C, D, H, W): separable per-axis linear resample
    size = size if size is not None else tuple(
        int(d * scale_factor) for d in x.shape[2:])
    for ax, out_d in zip((2, 3, 4), size):
        x = jnp.moveaxis(
            _linear_resize_last(jnp.moveaxis(x, ax, -1), int(out_d),
                                align_corners), -1, ax)
    return x


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """phi/kernels/fold_kernel (col2im — the inverse of unfold): x is
    (N, C*prod(k), L); returns (N, C, H, W) with overlapping patches
    summed."""
    def pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    oh, ow = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    nw = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            out = out.at[:, :, hi:hi + sh * nh:sh,
                         wj:wj + sw * nw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    p = list(paddings)  # (left, right, top, bottom, front, back)
    full = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    if mode == "constant":
        return jnp.pad(x, full, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return jnp.pad(x, full, mode=jmode)


# ------------------------------------------------------------ signal


def frame(x, frame_length, hop_length, axis=-1):
    """phi/kernels/frame_kernel: slide overlapping frames along axis.
    axis=-1: (..., n) → (..., frame_length, num_frames);
    axis=0:  (n, ...) → (num_frames, frame_length, ...)."""
    if axis not in (0, -1, x.ndim - 1):
        raise ValueError("frame: axis must be 0 or -1")
    first = axis == 0 and x.ndim > 1
    if first:
        x = jnp.moveaxis(x, 0, -1)
    n = x.shape[-1]
    num = (n - frame_length) // hop_length + 1
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(num)[:, None])
    out = x[..., idx]                       # (..., num, frame_length)
    if first:
        return jnp.moveaxis(out, (-2, -1), (0, 1))  # (num, fl, ...)
    return jnp.swapaxes(out, -1, -2)        # (..., frame_length, num)


def overlap_add(x, hop_length, axis=-1):
    """phi/kernels/overlap_add_kernel: inverse of frame.
    axis=-1: (..., frame_length, num) → (..., n);
    axis=0:  (num, frame_length, ...) → (n, ...)."""
    if axis not in (0, -1, x.ndim - 1):
        raise ValueError("overlap_add: axis must be 0 or -1")
    first = axis == 0 and x.ndim > 1
    if first:
        x = jnp.moveaxis(x, (0, 1), (-1, -2))  # (..., frame_length, num)
    fl, num = x.shape[-2], x.shape[-1]
    n = (num - 1) * hop_length + fl
    out = jnp.zeros(x.shape[:-2] + (n,), x.dtype)
    for i in range(num):
        out = out.at[..., i * hop_length:i * hop_length + fl].add(x[..., i])
    if first:
        out = jnp.moveaxis(out, -1, 0)
    return out


def stft(x, n_fft, hop_length=None, axis=-1, onesided=True, normalized=False):
    hop = hop_length or n_fft // 4
    frames = frame(x, n_fft, hop, axis=-1)   # (..., n_fft, num)
    frames = jnp.swapaxes(frames, -1, -2)    # (..., num, n_fft)
    spec = (jnp.fft.rfft(frames, axis=-1) if onesided
            else jnp.fft.fft(frames, axis=-1))
    if normalized:
        spec = spec / jnp.sqrt(n_fft)
    return jnp.swapaxes(spec, -1, -2)        # (..., freq, num)


def fft_c2c(x, axes=None, normalization="backward", forward=True):
    axes = tuple(axes) if axes is not None else (-1,)
    fn = jnp.fft.fftn if forward else jnp.fft.ifftn
    return fn(x, axes=axes, norm=normalization)


def fft_r2c(x, axes=None, normalization="backward", forward=True,
            onesided=True):
    axes = tuple(axes) if axes is not None else (-1,)
    if onesided:
        return jnp.fft.rfftn(x, axes=axes, norm=normalization)
    return jnp.fft.fftn(x.astype(jnp.complex64), axes=axes,
                        norm=normalization)


def fft_c2r(x, axes=None, normalization="backward", forward=False,
            last_dim_size=None):
    axes = tuple(axes) if axes is not None else (-1,)
    s = None
    if last_dim_size is not None:
        s = [x.shape[a] for a in axes]
        s[-1] = last_dim_size
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=normalization)


# ------------------------------------------------------------ sequence/text


def edit_distance(hyps, refs, hyp_lens, ref_lens, normalized=False):
    """phi/kernels/edit_distance_kernel: batched Levenshtein distance over
    padded int sequences, via a wavefront lax.scan."""
    b, hmax = hyps.shape
    rmax = refs.shape[1]

    def one(hyp, ref, hl, rl):
        row0 = jnp.arange(rmax + 1, dtype=jnp.float32)

        def step(prev, i):
            def inner(row, j):
                cost = jnp.where(hyp[i] == ref[j], 0.0, 1.0)
                val = jnp.minimum(
                    jnp.minimum(prev[j + 1] + 1.0, row[j] + 1.0),
                    prev[j] + cost)
                return row.at[j + 1].set(val), None

            row = jnp.zeros(rmax + 1, jnp.float32).at[0].set(i + 1.0)
            row, _ = jax.lax.scan(inner, row, jnp.arange(rmax))
            return row, row

        _, rows = jax.lax.scan(step, row0, jnp.arange(hmax))
        table = jnp.concatenate([row0[None], rows], axis=0)
        d = table[hl, rl]  # distance at the true (unpadded) lengths
        return jnp.where(normalized, d / jnp.maximum(rl, 1), d)

    return jax.vmap(one)(hyps, refs, hyp_lens, ref_lens)


# ------------------------------------------------------------ detection


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    """phi/kernels/box_coder_kernel: encode/decode boxes against priors."""
    pw = prior_box[:, 2] - prior_box[:, 0] + (0 if box_normalized else 1)
    ph = prior_box[:, 3] - prior_box[:, 1] + (0 if box_normalized else 1)
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    var = (prior_box_var if prior_box_var is not None
           else jnp.ones((1, 4), prior_box.dtype))
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + (0 if box_normalized else 1)
        th = target_box[:, 3] - target_box[:, 1] + (0 if box_normalized else 1)
        tx = target_box[:, 0] + tw * 0.5
        ty = target_box[:, 1] + th * 0.5
        out = jnp.stack([
            (tx[:, None] - px[None, :]) / pw[None, :],
            (ty[:, None] - py[None, :]) / ph[None, :],
            jnp.log(tw[:, None] / pw[None, :]),
            jnp.log(th[:, None] / ph[None, :]),
        ], axis=-1)
        return out / var.reshape(1, -1, 4)
    # decode: target (N, M, 4) deltas against priors
    t = target_box * var.reshape(1, -1, 4)
    ox = t[..., 0] * pw + px
    oy = t[..., 1] * ph + py
    ow = jnp.exp(t[..., 2]) * pw
    oh = jnp.exp(t[..., 3]) * ph
    sub = 0 if box_normalized else 1
    return jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                      ox + ow * 0.5 - sub, oy + oh * 0.5 - sub], axis=-1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variances=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              step_w=0.0, step_h=0.0, offset=0.5):
    """phi/kernels/prior_box_kernel: SSD prior boxes for one feature map."""
    fh, fw = input.shape[2], input.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / fw
    sh = step_h or ih / fh
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]
    boxes = []
    for i in range(fh):
        for j in range(fw):
            cx = (j + offset) * sw
            cy = (i + offset) * sh
            cell = []
            for k, ms in enumerate(min_sizes):
                cell.append((cx - ms / 2, cy - ms / 2, cx + ms / 2,
                             cy + ms / 2))
                if max_sizes:
                    s = math.sqrt(ms * max_sizes[k])
                    cell.append((cx - s / 2, cy - s / 2, cx + s / 2,
                                 cy + s / 2))
                for a in ars:
                    if abs(a - 1.0) < 1e-6:
                        continue
                    bw = ms * math.sqrt(a)
                    bh = ms / math.sqrt(a)
                    cell.append((cx - bw / 2, cy - bh / 2, cx + bw / 2,
                                 cy + bh / 2))
            boxes.extend(cell)
    out = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    out = out / np.asarray([iw, ih, iw, ih], np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          out.shape).copy()
    return jnp.asarray(out), jnp.asarray(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """phi/kernels/yolo_box_kernel: decode YOLOv3 head to boxes+scores."""
    n, c, h, w = x.shape
    an = len(anchors) // 2
    x = x.reshape(n, an, 5 + class_num, h, w)
    grid_x = jnp.arange(w, dtype=jnp.float32).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h, dtype=jnp.float32).reshape(1, 1, h, 1)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * scale_x_y
          - 0.5 * (scale_x_y - 1.0) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, an, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, an, 1, 1)
    # per-axis input sizes (yolo_box_kernel.cc:48-49): non-square maps keep
    # distinct w/h normalizers
    bw = jnp.exp(x[:, :, 2]) * aw / (downsample_ratio * w)
    bh = jnp.exp(x[:, :, 3]) * ah / (downsample_ratio * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    imh = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
    imw = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
    x0 = (bx - bw / 2.0) * imw
    y0 = (by - bh / 2.0) * imh
    x1 = (bx + bw / 2.0) * imw
    y1 = (by + bh / 2.0) * imh
    if clip_bbox:
        x0 = jnp.clip(x0, 0.0, imw - 1)
        y0 = jnp.clip(y0, 0.0, imh - 1)
        x1 = jnp.clip(x1, 0.0, imw - 1)
        y1 = jnp.clip(y1, 0.0, imh - 1)
    boxes = jnp.stack([x0, y0, x1, y1], axis=-1).reshape(n, -1, 4)
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    keep = conf.reshape(n, -1, 1) >= conf_thresh
    return boxes * keep, scores * keep


def matrix_rank(x, tol=None, hermitian=False, use_default_tol=True):
    """phi/kernels/matrix_rank_kernel: rank via singular values."""
    if hermitian:
        s = jnp.abs(jnp.linalg.eigvalsh(x))
    else:
        s = jnp.linalg.svd(x, compute_uv=False)
    if tol is None:
        tol = s.max(axis=-1) * max(x.shape[-2], x.shape[-1]) * \
            jnp.finfo(x.dtype).eps
        tol = tol[..., None]
    return jnp.sum((s > tol).astype(jnp.int64), axis=-1)
